"""RecordReader bridge — the DataVec layer.

Parity targets: reference deeplearning4j-core
datasets/datavec/RecordReaderDataSetIterator.java and
SequenceRecordReaderDataSetIterator.java, with the datavec-api readers
they consume (CSVRecordReader, CSVSequenceRecordReader, ImageRecordReader).

Readers yield plain python/numpy records; the iterators assemble padded,
masked DataSet batches — the ETL work stays on host (numpy), only the
finished batches go to device, which is the right TPU split (SURVEY §2.4:
feed the chip, don't compute on it).
"""

from __future__ import annotations

import csv
import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .dataset import DataSet
from .iterators import DataSetIterator


# ---------------------------------------------------------------------------
# record readers (datavec-api parity)
# ---------------------------------------------------------------------------


class CSVRecordReader:
    """Line-per-record CSV reader (reference CSVRecordReader: skipNumLines,
    delimiter).  Yields List[str] records."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._records: Optional[List[List[str]]] = None

    def initialize(self, path: str) -> "CSVRecordReader":
        with open(path, newline="") as f:
            rows = list(csv.reader(f, delimiter=self.delimiter))
        self._records = [r for r in rows[self.skip_lines:] if r]
        return self

    def __iter__(self) -> Iterator[List[str]]:
        if self._records is None:
            raise ValueError("call initialize(path) first")
        return iter(self._records)

    def __len__(self) -> int:
        return len(self._records or [])


class CSVSequenceRecordReader:
    """One CSV file per sequence (reference CSVSequenceRecordReader).
    initialize() takes a list of file paths; each yields [T, cols] rows."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._paths: List[str] = []

    def initialize(self, paths: Sequence[str]) -> "CSVSequenceRecordReader":
        self._paths = list(paths)
        return self

    def __iter__(self) -> Iterator[List[List[str]]]:
        for p in self._paths:
            with open(p, newline="") as f:
                rows = list(csv.reader(f, delimiter=self.delimiter))
            yield [r for r in rows[self.skip_lines:] if r]

    def __len__(self) -> int:
        return len(self._paths)


class ImageRecordReader:
    """Directory-of-images reader, label = parent directory name
    (reference datavec ImageRecordReader + ParentPathLabelGenerator).
    Yields (image [h,w,c] float32 in [0,1], label_index)."""

    def __init__(self, height: int, width: int, channels: int = 3):
        self.height = height
        self.width = width
        self.channels = channels
        self.labels: List[str] = []
        self._files: List[Tuple[str, int]] = []

    def initialize(self, root: str,
                   extensions: Tuple[str, ...] = (".png", ".jpg", ".jpeg", ".bmp")
                   ) -> "ImageRecordReader":
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class subdirectories under {root}")
        self.labels = classes
        self._files = []
        for idx, cls in enumerate(classes):
            cdir = os.path.join(root, cls)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(extensions):
                    self._files.append((os.path.join(cdir, fn), idx))
        return self

    def __iter__(self) -> Iterator[Tuple[np.ndarray, int]]:
        from PIL import Image

        for path, idx in self._files:
            img = Image.open(path)
            img = img.convert("L" if self.channels == 1 else "RGB")
            img = img.resize((self.width, self.height))
            arr = np.asarray(img, np.float32) / 255.0
            if self.channels == 1:
                arr = arr[..., None]
            yield arr, idx

    def __len__(self) -> int:
        return len(self._files)


# ---------------------------------------------------------------------------
# iterators (deeplearning4j-core datasets/datavec parity)
# ---------------------------------------------------------------------------


class _AssembledIterator(DataSetIterator):
    """Shared reset/has_next/next plumbing: subclasses implement
    ``_assemble() -> List[DataSet]``; batches materialize lazily on first
    use and are cached, so the full DataSetIterator contract works (Async
    prefetch wrappers, EarlyTermination, MultipleEpochs all drive it)."""

    _cache: Optional[List[DataSet]] = None
    _pos: int = 0

    def _assemble(self) -> List[DataSet]:
        raise NotImplementedError

    def _ensure(self) -> List[DataSet]:
        if self._cache is None:
            self._cache = self._assemble()
        return self._cache

    def reset(self) -> None:
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._ensure())

    def next(self) -> DataSet:
        b = self._ensure()[self._pos]
        self._pos += 1
        return b

    def total_examples(self) -> int:
        return sum(b.num_examples() for b in self._ensure())


class RecordReaderDataSetIterator(_AssembledIterator):
    """CSV records → classification/regression DataSet batches (reference
    RecordReaderDataSetIterator: labelIndex + numPossibleLabels, or
    regression=True with labelIndexFrom/To)."""

    def __init__(self, reader, batch_size: int, label_index: int,
                 num_classes: Optional[int] = None, regression: bool = False,
                 label_index_to: Optional[int] = None):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = label_index_to if label_index_to is not None else label_index
        if not regression and num_classes is None:
            raise ValueError("num_classes is required for classification")

    def _assemble(self) -> List[DataSet]:
        feats, labels = [], []
        for rec in self.reader:
            vals = [v for v in rec]
            li, lt = self.label_index, self.label_index_to
            lab_vals = vals[li:lt + 1]
            feat_vals = vals[:li] + vals[lt + 1:]
            feats.append([float(v) for v in feat_vals])
            labels.append([float(v) for v in lab_vals])
        xs = np.asarray(feats, np.float32)
        if self.regression:
            ys = np.asarray(labels, np.float32)
        else:
            idx = np.asarray(labels, np.float32).astype(np.int32).reshape(-1)
            ys = np.eye(self.num_classes, dtype=np.float32)[idx]
        ds = DataSet(xs, ys)
        return ds.batch_by(self.batch_size)


class ImageRecordReaderDataSetIterator(_AssembledIterator):
    """Image records → [mb,h,w,c] DataSet batches with one-hot labels."""

    def __init__(self, reader: ImageRecordReader, batch_size: int):
        self.reader = reader
        self.batch_size = batch_size

    def _assemble(self) -> List[DataSet]:
        num_classes = len(self.reader.labels)
        xs, ys = [], []
        for arr, idx in self.reader:
            xs.append(arr)
            ys.append(idx)
        ds = DataSet(np.stack(xs),
                     np.eye(num_classes, dtype=np.float32)[np.asarray(ys)])
        return ds.batch_by(self.batch_size)


class SequenceRecordReaderDataSetIterator(_AssembledIterator):
    """Aligned feature/label sequence readers → padded+masked rank-3
    batches.  Sequences are LEFT-aligned (data from t=0, zero padding +
    mask 0 at the tail — the reference's ALIGN_START mode); masked
    consumers (RnnOutputLayer loss, LastTimeStep) handle variable lengths
    through the masks."""

    def __init__(self, features_reader, labels_reader, batch_size: int,
                 num_classes: Optional[int] = None, regression: bool = False):
        self.features_reader = features_reader
        self.labels_reader = labels_reader
        self.batch_size = batch_size
        self.num_classes = num_classes
        self.regression = regression
        if not regression and num_classes is None:
            raise ValueError("num_classes is required for classification")

    def _assemble(self) -> List[DataSet]:
        fseqs = [np.asarray([[float(v) for v in row] for row in seq], np.float32)
                 for seq in self.features_reader]
        lseqs = [np.asarray([[float(v) for v in row] for row in seq], np.float32)
                 for seq in self.labels_reader]
        if len(fseqs) != len(lseqs):
            raise ValueError(f"{len(fseqs)} feature sequences vs {len(lseqs)} label")
        out = []
        for s in range(0, len(fseqs), self.batch_size):
            fs = fseqs[s:s + self.batch_size]
            ls = lseqs[s:s + self.batch_size]
            T = max(len(a) for a in fs)
            mb = len(fs)
            fdim = fs[0].shape[1]
            x = np.zeros((mb, T, fdim), np.float32)
            fm = np.zeros((mb, T), np.float32)
            if self.regression:
                ldim = ls[0].shape[1]
            else:
                ldim = self.num_classes
            y = np.zeros((mb, T, ldim), np.float32)
            lm = np.zeros((mb, T), np.float32)
            for i, (fa, la) in enumerate(zip(fs, ls)):
                x[i, :len(fa)] = fa
                fm[i, :len(fa)] = 1.0
                if self.regression:
                    y[i, :len(la)] = la
                else:
                    idx = la.astype(np.int32).reshape(-1)
                    y[i, np.arange(len(la)), idx] = 1.0
                lm[i, :len(la)] = 1.0
            out.append(DataSet(x, y, features_mask=fm, labels_mask=lm))
        return out
