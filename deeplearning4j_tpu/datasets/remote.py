"""Remote-storage seam — the explicit decision on the reference's AWS
tooling (VERDICT round 2, Missing #9).

Parity targets:
  - deeplearning4j-aws/.../s3/reader/BaseS3DataSetIterator.java — stream
    serialized DataSets out of an S3 bucket;
  - deeplearning4j-aws/.../ec2/provision/ClusterSetup.java — EC2 cluster
    provisioning.

Decision, stated explicitly rather than left silent:
  * Data-from-remote-storage IS supported, via the pluggable
    ``StorageProvider`` registry below.  The wire format is the framework's
    own model/DataSet serialization; the transport is a provider keyed by
    URI scheme.  A ``file://`` provider ships (and is what CI exercises in
    this zero-egress environment); an ``s3://`` provider registers itself
    only when boto3 is importable, and raises a clear error otherwise —
    the seam, signatures and tests are the deliverable, live-cloud code
    cannot be exercised here.
  * Cluster PROVISIONING (ClusterSetup.java) is a documented NON-GOAL:
    TPU-native scale-out is placed by the launcher (GKE/Ray/xmanager) and
    wired by ``parallel.distributed.initialize()`` — re-implementing an
    EC2 bootstrapper would be dead code on TPU infrastructure.
"""

from __future__ import annotations

import io
import os
import urllib.parse
from typing import Callable, Dict, Iterable, List, Optional

import numpy as np

from .dataset import DataSet
from .iterators import DataSetIterator


class StorageProvider:
    """Minimal object-storage interface: list keys under a prefix, open a
    key as a binary file object (fsspec's role, kept dependency-free)."""

    scheme: str = ""

    def list(self, uri: str) -> List[str]:
        raise NotImplementedError

    def open(self, uri: str):
        raise NotImplementedError


_PROVIDERS: Dict[str, StorageProvider] = {}


def register_provider(provider: StorageProvider) -> None:
    _PROVIDERS[provider.scheme] = provider


def get_provider(uri: str) -> StorageProvider:
    scheme = urllib.parse.urlparse(uri).scheme or "file"
    if scheme not in _PROVIDERS:
        raise ValueError(
            f"no storage provider registered for scheme '{scheme}' "
            f"(have: {sorted(_PROVIDERS)}); register_provider() a "
            f"StorageProvider for it")
    return _PROVIDERS[scheme]


class LocalProvider(StorageProvider):
    """file:// (or bare-path) provider — also the CI stand-in for remote
    stores in zero-egress environments."""

    scheme = "file"

    @staticmethod
    def _path(uri: str) -> str:
        p = urllib.parse.urlparse(uri)
        return (p.path if not p.netloc else os.path.join("/", p.netloc + p.path)) \
            if p.scheme else uri

    def list(self, uri: str) -> List[str]:
        root = self._path(uri)
        if os.path.isfile(root):
            return [root]
        out = []
        for dirpath, _, files in os.walk(root):
            for f in sorted(files):
                out.append(os.path.join(dirpath, f))
        return sorted(out)

    def open(self, uri: str):
        return open(self._path(uri), "rb")


class S3Provider(StorageProvider):
    """s3:// via boto3 (reference BaseS3DataSetIterator.java's transport).
    Constructed lazily: importing this module never requires boto3; using
    s3:// URIs without it raises with instructions instead of ImportError
    somewhere deep in a data loader."""

    scheme = "s3"

    def __init__(self):
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "s3:// URIs need boto3 (pip install boto3) and AWS "
                "credentials in the environment") from e
        import boto3
        self._client = boto3.client("s3")

    @staticmethod
    def _split(uri: str):
        p = urllib.parse.urlparse(uri)
        return p.netloc, p.path.lstrip("/")

    def list(self, uri: str) -> List[str]:
        bucket, prefix = self._split(uri)
        keys, token = [], None
        while True:
            kw = {"Bucket": bucket, "Prefix": prefix}
            if token:
                kw["ContinuationToken"] = token
            resp = self._client.list_objects_v2(**kw)
            keys += [f"s3://{bucket}/{o['Key']}" for o in resp.get("Contents", [])]
            token = resp.get("NextContinuationToken")
            if not token:
                return keys

    def open(self, uri: str):
        bucket, key = self._split(uri)
        buf = io.BytesIO()
        self._client.download_fileobj(bucket, key, buf)
        buf.seek(0)
        return buf


register_provider(LocalProvider())


def save_dataset(ds: DataSet, fileobj) -> None:
    """One DataSet → one .npz object (the wire format RemoteDataSetIterator
    reads; the reference streams Nd4j-serialized DataSets the same way)."""
    arrs = {}
    if ds.features is not None:
        arrs["features"] = np.asarray(ds.features)
    if ds.labels is not None:
        arrs["labels"] = np.asarray(ds.labels)
    if ds.features_mask is not None:
        arrs["features_mask"] = np.asarray(ds.features_mask)
    if ds.labels_mask is not None:
        arrs["labels_mask"] = np.asarray(ds.labels_mask)
    np.savez(fileobj, **arrs)


def load_dataset(fileobj) -> DataSet:
    with np.load(fileobj) as z:
        return DataSet(z.get("features"), z.get("labels"),
                       z.get("features_mask"), z.get("labels_mask"))


class RemoteDataSetIterator(DataSetIterator):
    """Stream serialized DataSets from any registered provider (reference
    BaseS3DataSetIterator.java iterates bucket keys the same way).

    >>> it = RemoteDataSetIterator("file:///data/train/")   # or s3://...
    >>> net.fit(it, epochs=3)
    """

    def __init__(self, uri: str, suffix: str = ".npz",
                 provider: Optional[StorageProvider] = None):
        self.provider = provider or get_provider(uri)
        self.keys = [k for k in self.provider.list(uri) if k.endswith(suffix)]
        if not self.keys:
            raise FileNotFoundError(f"no '{suffix}' objects under {uri}")
        self._pos = 0

    def reset(self) -> None:
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self.keys)

    def next(self) -> DataSet:
        key = self.keys[self._pos]
        self._pos += 1
        with self.provider.open(key) as f:
            return load_dataset(f)

    def total_examples(self) -> Optional[int]:
        return None
