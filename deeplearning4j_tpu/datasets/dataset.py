"""DataSet / MultiDataSet — minibatch containers.

Parity with ND4J ``org.nd4j.linalg.dataset.DataSet`` (features, labels,
featuresMask, labelsMask) and ``MultiDataSet`` (lists of each).  Arrays are
numpy on the host; device placement happens inside the jit'd step — or
ahead of it via the double-buffered async puts of
``device_prefetch.DevicePrefetchIterator``, whose batches carry
device-resident jax Arrays in the same fields (consumers pass them
through untouched).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: Optional[np.ndarray] = None
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def get_rows(self, idx) -> "DataSet":
        """Row-select all four fields by index array/permutation — the one
        place the 4-field reconstruction lives (shuffle / sampling / k-fold
        all route through here)."""
        pick = lambda a: None if a is None else a[idx]
        return DataSet(self.features[idx], pick(self.labels),
                       pick(self.features_mask), pick(self.labels_mask))

    def split_test_and_train(self, n_train: int) -> tuple["DataSet", "DataSet"]:
        def cut(a, lo, hi):
            return None if a is None else a[lo:hi]
        n = self.num_examples()
        return (
            DataSet(self.features[:n_train], cut(self.labels, 0, n_train),
                    cut(self.features_mask, 0, n_train), cut(self.labels_mask, 0, n_train)),
            DataSet(self.features[n_train:], cut(self.labels, n_train, n),
                    cut(self.features_mask, n_train, n), cut(self.labels_mask, n_train, n)),
        )

    def shuffle(self, seed: Optional[int] = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        return self.get_rows(rng.permutation(self.num_examples()))

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        for i in range(0, self.num_examples(), batch_size):
            cut = lambda a: None if a is None else a[i:i + batch_size]
            out.append(DataSet(self.features[i:i + batch_size], cut(self.labels),
                               cut(self.features_mask), cut(self.labels_mask)))
        return out

    @staticmethod
    def merge(sets: Sequence["DataSet"]) -> "DataSet":
        cat = lambda xs: None if xs[0] is None else np.concatenate(xs, axis=0)
        return DataSet(
            np.concatenate([d.features for d in sets], axis=0),
            cat([d.labels for d in sets]),
            cat([d.features_mask for d in sets]),
            cat([d.labels_mask for d in sets]),
        )


@dataclasses.dataclass
class MultiDataSet:
    features: List[np.ndarray]
    labels: List[np.ndarray]
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])
