"""Tensor pub-sub streaming — the reference's Kafka/Camel transport role.

Parity targets: dl4j-streaming's Kafka NDArray pipeline —
``deeplearning4j-scaleout/dl4j-streaming/src/main/java/org/deeplearning4j/
streaming/kafka/NDArrayPublisher.java`` (serialize INDArray → Kafka topic),
``NDArrayConsumer.java`` (topic → INDArray), and the Camel routes that
feed training from a stream.

Zero-egress TPU inversion: the broker is a stdlib TCP server speaking
length-prefixed ``.npy`` frames — no Kafka cluster, no external daemon,
same topology (N publishers → topic → N subscribers, fan-out to all
subscribers of a topic).  The wire format is numpy's own serialization,
so any language with an npy reader interoperates.  For training ingest,
``StreamingDataSetIterator`` pairs a features topic with a labels topic
the way the reference's Camel route assembles DataSets.

Frame protocol (all little-endian):
  publisher → broker:  b"P" + u32 topic_len + topic + frames
  subscriber → broker: b"S" + u32 topic_len + topic, then reads frames
  frame: u64 payload_len + payload (npy bytes)
"""

from __future__ import annotations

import io
import logging
import queue
import socket
import struct
import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..datasets.dataset import DataSet
from ..datasets.iterators import DataSetIterator

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")
_U32 = struct.Struct("<I")


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    return _recv_exact(sock, n)


def _array_to_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def _bytes_to_array(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


class _Subscriber:
    """One subscriber socket behind a bounded frame queue + writer thread.

    Publishers enqueue; a single writer thread owns the socket, so frames
    from concurrent publishers can never interleave mid-``sendall``.
    Delivery is LOSSLESS: when a subscriber's queue fills, ``offer``
    blocks the relaying publisher (the same backpressure the previous
    direct-``sendall`` design got from TCP) — dropping frames would
    silently skew zipped-topic consumers like StreamingDataSetIterator's
    features/labels pairing.  The queue still softens head-of-line
    blocking: a slow subscriber delays the topic only once it falls
    ``max_queue`` frames behind, instead of immediately.
    """

    def __init__(self, sock: socket.socket, max_queue: int = 256):
        self.sock = sock
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue(max_queue)
        self.alive = True
        threading.Thread(target=self._writer, daemon=True).start()

    def offer(self, frame: bytes) -> None:
        while self.alive:
            try:
                self._q.put(frame, timeout=0.1)  # recheck alive while full
                return
            except queue.Full:
                continue

    def _writer(self) -> None:
        while True:
            frame = self._q.get()
            if frame is None:
                break
            try:
                _send_frame(self.sock, frame)
            except OSError:
                break
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass

    def close(self) -> None:
        self.alive = False
        try:
            self._q.put_nowait(None)
        except queue.Full:
            self.sock.close()  # writer will die on next send


class TensorBroker:
    """In-process topic broker (the Kafka cluster's role, one process).

    >>> broker = TensorBroker().start()          # port auto-assigned
    >>> pub = NDArrayPublisher(broker.address, "features").connect()
    >>> sub = NDArrayConsumer(broker.address, "features").connect()
    >>> pub.publish(np.ones((2, 3)))
    >>> sub.next()                              # → the array
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._srv: Optional[socket.socket] = None
        self._subs: Dict[str, List[_Subscriber]] = {}
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._running = False

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def start(self) -> "TensorBroker":
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self.host, self.port))
        self.port = self._srv.getsockname()[1]
        self._srv.listen()
        self._running = True
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        role = None
        try:
            role = _recv_exact(conn, 1)
            tlen_raw = _recv_exact(conn, _U32.size)
            if role is None or tlen_raw is None:
                return
            (tlen,) = _U32.unpack(tlen_raw)
            topic_raw = _recv_exact(conn, tlen)
            if topic_raw is None:
                return
            topic = topic_raw.decode()
            if role == b"S":
                with self._lock:
                    self._subs.setdefault(topic, []).append(_Subscriber(conn))
                return  # frames are pushed by publishers; keep socket open
            while True:  # publisher: relay frames to every subscriber
                frame = _recv_frame(conn)
                if frame is None:
                    return
                with self._lock:
                    subs = list(self._subs.get(topic, []))
                    dead = [s for s in subs if not s.alive]
                    if dead:
                        self._subs[topic] = [s for s in subs if s.alive]
                for d in dead:
                    # visible trail for lossless-delivery debugging: frames
                    # queued at subscriber death are discarded, and offer()
                    # silently skips culled subscribers
                    pending = d._q.qsize()
                    logger.info(
                        "pubsub: culled dead subscriber on topic %r "
                        "(%d queued frame(s) discarded)", topic, pending)
                for s in subs:
                    s.offer(frame)
        finally:
            if role == b"P":
                conn.close()

    def stop(self) -> None:
        self._running = False
        if self._srv is not None:
            self._srv.close()
        with self._lock:
            for subs in self._subs.values():
                for s in subs:
                    s.close()
                    s.sock.close()
            self._subs.clear()


class NDArrayPublisher:
    """Publish numpy/jax arrays to a broker topic (reference
    NDArrayPublisher.java: INDArray → serialized bytes → topic)."""

    def __init__(self, address: Tuple[str, int], topic: str):
        self.address = address
        self.topic = topic
        self._sock: Optional[socket.socket] = None

    def connect(self) -> "NDArrayPublisher":
        self._sock = socket.create_connection(self.address)
        t = self.topic.encode()
        self._sock.sendall(b"P" + _U32.pack(len(t)) + t)
        return self

    def publish(self, arr) -> None:
        if self._sock is None:
            raise RuntimeError("connect() first")
        _send_frame(self._sock, _array_to_bytes(arr))

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None


class NDArrayConsumer:
    """Subscribe to a broker topic and read arrays (reference
    NDArrayConsumer.java).  Frames received on a background thread queue
    up; ``next()`` blocks with an optional timeout."""

    def __init__(self, address: Tuple[str, int], topic: str,
                 max_queue: int = 1024):
        self.address = address
        self.topic = topic
        self._sock: Optional[socket.socket] = None
        self._q: "queue.Queue[Optional[np.ndarray]]" = queue.Queue(max_queue)

    def connect(self) -> "NDArrayConsumer":
        self._sock = socket.create_connection(self.address)
        t = self.topic.encode()
        self._sock.sendall(b"S" + _U32.pack(len(t)) + t)
        threading.Thread(target=self._pump, daemon=True).start()
        return self

    def _pump(self) -> None:
        while True:
            try:
                frame = _recv_frame(self._sock)
            except OSError:
                frame = None
            if frame is None:
                self._q.put(None)  # end-of-stream marker
                return
            self._q.put(_bytes_to_array(frame))

    def next(self, timeout: Optional[float] = 10.0) -> Optional[np.ndarray]:
        """Next array, or None once the stream closed."""
        return self._q.get(timeout=timeout)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            arr = self.next()
            if arr is None:
                return
            yield arr

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()


class StreamingDataSetIterator(DataSetIterator):
    """Assemble DataSets from a features topic + labels topic (the Camel
    route's role: two streams zipped into training batches).  Bounded by
    ``max_batches`` per epoch so ``fit(..., epochs=1)`` terminates."""

    def __init__(self, address: Tuple[str, int],
                 features_topic: str = "features",
                 labels_topic: str = "labels",
                 max_batches: Optional[int] = None,
                 timeout: float = 10.0):
        self._features = NDArrayConsumer(address, features_topic).connect()
        self._labels = NDArrayConsumer(address, labels_topic).connect()
        self.max_batches = max_batches
        self.timeout = timeout
        self._count = 0
        self._pending_x: Optional[np.ndarray] = None

    def reset(self) -> None:
        self._count = 0

    def has_next(self) -> bool:
        return self.max_batches is None or self._count < self.max_batches

    def next(self) -> DataSet:
        # Iterator-protocol contract (datasets/iterators.py consumers like
        # AsyncDataSetIterator expect StopIteration, never queue.Empty).
        # A features frame whose labels frame hasn't arrived yet is stashed
        # so a later next() pairs it with ITS labels — a labels-side lag
        # must never skew the x/y pairing for the rest of the stream.
        try:
            x = self._pending_x if self._pending_x is not None \
                else self._features.next(timeout=self.timeout)
        except queue.Empty:
            raise StopIteration from None
        self._pending_x = x
        try:
            y = self._labels.next(timeout=self.timeout)
        except queue.Empty:
            raise StopIteration from None
        self._pending_x = None
        if x is None or y is None:
            raise StopIteration
        self._count += 1
        return DataSet(x, y)

    def __iter__(self):
        self.reset()
        while self.has_next():
            try:
                yield self.next()
            except StopIteration:
                return

    def close(self) -> None:
        self._features.close()
        self._labels.close()
