from .pubsub import (
    NDArrayConsumer,
    NDArrayPublisher,
    StreamingDataSetIterator,
    TensorBroker,
)
