"""``python -m deeplearning4j_tpu`` → CLI (see cli.py)."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
