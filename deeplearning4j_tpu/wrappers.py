"""sklearn-style estimator wrappers.

Parity target: the reference ecosystem's ScikitLearn-ish wrappers
(deeplearning4j-scaleout/dl4j-streaming's simple wrappers + the
community's Keras-like fit/predict surface).  ``NeuralNetClassifier`` /
``NeuralNetRegressor`` adapt any MultiLayerConfiguration (or a builder
thereof) to fit(X, y) / predict(X) / predict_proba(X) / score(X, y) with
numpy in, numpy out — so framework models drop into sklearn pipelines,
grid searches, and cross-validation loops.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Union

import numpy as np

from .datasets import DataSet, ListDataSetIterator
from .nn.multilayer import MultiLayerConfiguration, MultiLayerNetwork


class _BaseWrapper:
    def __init__(self, conf: Union[MultiLayerConfiguration, Callable[[], MultiLayerConfiguration]],
                 epochs: int = 10, batch_size: int = 128, seed: int = 12345,
                 shuffle: bool = True):
        self.conf = conf
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.shuffle = shuffle
        self.net_: Optional[MultiLayerNetwork] = None
        self.losses_: List[float] = []

    # sklearn contract
    def get_params(self, deep: bool = True) -> dict:
        return {"conf": self.conf, "epochs": self.epochs,
                "batch_size": self.batch_size, "seed": self.seed,
                "shuffle": self.shuffle}

    def set_params(self, **params) -> "_BaseWrapper":
        valid = set(self.get_params())
        for k, v in params.items():
            if k not in valid:  # sklearn contract: constructor params only
                raise ValueError(f"unknown parameter {k} — valid: {sorted(valid)}")
            setattr(self, k, v)
        return self

    def _materialize(self) -> MultiLayerNetwork:
        conf = self.conf() if callable(self.conf) else self.conf
        if not isinstance(conf, MultiLayerConfiguration):
            raise TypeError("conf must be a MultiLayerConfiguration or a "
                            "zero-arg factory returning one")
        net = MultiLayerNetwork(conf)
        net.init()
        return net

    def _fit(self, X: np.ndarray, y2d: np.ndarray) -> "_BaseWrapper":
        self.net_ = self._materialize()
        ds = DataSet(np.asarray(X, np.float32), np.asarray(y2d, np.float32))
        if self.shuffle:
            ds = ds.shuffle(self.seed)
        it = ListDataSetIterator(ds.batch_by(self.batch_size))
        self.losses_ = self.net_.fit(it, epochs=self.epochs)
        return self

    def _check_fitted(self) -> MultiLayerNetwork:
        if self.net_ is None:
            raise RuntimeError("call fit(X, y) before predicting")
        return self.net_


class NeuralNetClassifier(_BaseWrapper):
    """fit(X, y) with integer class labels (or one-hot); predict returns
    class indices, predict_proba the softmax outputs, score the accuracy."""

    def fit(self, X, y) -> "NeuralNetClassifier":
        y = np.asarray(y)
        if y.ndim == 1:
            self.classes_ = np.unique(y)
            index = {c: i for i, c in enumerate(self.classes_)}
            onehot = np.zeros((len(y), len(self.classes_)), np.float32)
            onehot[np.arange(len(y)), [index[c] for c in y]] = 1.0
        else:
            self.classes_ = np.arange(y.shape[1])
            onehot = y.astype(np.float32)
        return self._fit(X, onehot)

    def predict_proba(self, X) -> np.ndarray:
        return np.asarray(self._check_fitted().output(np.asarray(X, np.float32)))

    def predict(self, X) -> np.ndarray:
        idx = np.argmax(self.predict_proba(X), axis=-1)
        return self.classes_[idx]

    def score(self, X, y) -> float:
        y = np.asarray(y)
        if y.ndim == 2:  # one-hot labels (fit accepts them too)
            y = self.classes_[np.argmax(y, axis=1)]
        return float(np.mean(self.predict(X) == y))


class NeuralNetRegressor(_BaseWrapper):
    """fit(X, y) with continuous targets; predict returns raw outputs,
    score the R² coefficient (sklearn convention)."""

    def fit(self, X, y) -> "NeuralNetRegressor":
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        return self._fit(X, y)

    def predict(self, X) -> np.ndarray:
        out = np.asarray(self._check_fitted().output(np.asarray(X, np.float32)))
        return out[:, 0] if out.shape[-1] == 1 else out

    def score(self, X, y) -> float:
        """R², sklearn convention: per-output means, uniform average."""
        y = np.asarray(y, np.float32)
        pred = np.asarray(self.predict(X), np.float32)
        y2 = y.reshape(len(y), -1)
        p2 = pred.reshape(len(pred), -1)
        if y2.shape != p2.shape:
            raise ValueError(f"target shape {y.shape} incompatible with "
                             f"predictions {pred.shape}")
        ss_res = np.sum((y2 - p2) ** 2, axis=0)
        ss_tot = np.sum((y2 - y2.mean(axis=0)) ** 2, axis=0)
        return float(np.mean(1.0 - ss_res / np.maximum(ss_tot, 1e-12)))
