"""Pretrained-weight loading with checksum-verified local cache.

Parity target: reference zoo/ZooModel.java:40-81 (initPretrained:
pretrainedUrl → download to ~/.deeplearning4j/models/<name> → checksum
via Adler32 → restore).  This environment is zero-egress, so the transport
is a local file (or a pre-populated cache directory), but the mechanism —
cache layout, checksum verification, corrupt-file eviction, restore into
the matching architecture — is the same.  Checkpoints are the framework's
zip format (utils/serializer.py), the analog of the reference's saved
.zip models.
"""

from __future__ import annotations

import os
import shutil
import zlib
from typing import Optional

DEFAULT_CACHE = os.path.expanduser("~/.deeplearning4j_tpu/models")


def checksum(path: str) -> int:
    """Adler-32 over the file (matches ZooModel's checksum choice)."""
    value = 1
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            value = zlib.adler32(chunk, value)
    return value & 0xFFFFFFFF


class PretrainedType:
    """Reference PretrainedType enum (dataset the weights were fit on)."""

    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"
    IRIS = "iris"


#: weights shipped IN the package (trained on real embedded data — no
#: egress required): (model_name, pretrained_type) → (relative path under
#: models/weights/, adler32 checksum).  Hosted large-model artifacts are
#: an at-release task; this registry is the same seam they will use.
#: iris_mlp: 4→16tanh→8tanh→3softmax trained on Fisher's Iris (the 150
#: embedded rows, raw un-normalized features), 98.7% train accuracy.
BUILTIN_WEIGHTS = {
    ("iris_mlp", PretrainedType.IRIS): ("iris_mlp_iris.zip", 1686618174),
}


def cached_path(model_name: str, pretrained_type: str = PretrainedType.IMAGENET,
                cache_dir: Optional[str] = None) -> str:
    cache = cache_dir or DEFAULT_CACHE
    return os.path.join(cache, model_name, f"{model_name}_{pretrained_type}.zip")


def install_weights(model_name: str, source_path: str,
                    pretrained_type: str = PretrainedType.IMAGENET,
                    cache_dir: Optional[str] = None) -> str:
    """Copy a weights zip into the cache (the zero-egress stand-in for the
    reference's download step).  Returns the cached path."""
    dst = cached_path(model_name, pretrained_type, cache_dir)
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    shutil.copyfile(source_path, dst)
    return dst


def init_pretrained(model_name: str,
                    pretrained_type: str = PretrainedType.IMAGENET,
                    expected_checksum: Optional[int] = None,
                    cache_dir: Optional[str] = None,
                    local_file: Optional[str] = None):
    """Load a pretrained model (reference ZooModel.initPretrained:40-81).

    Resolution order: explicit ``local_file``, then the cache, then the
    package's BUILTIN_WEIGHTS (checksum always enforced for builtins).
    When ``expected_checksum`` is given and the cached file mismatches, it
    is evicted and a clear error raised (the reference's corrupt-download
    retry, minus the download)."""
    from ..utils.serializer import load_model

    path = local_file or cached_path(model_name, pretrained_type, cache_dir)
    if not os.path.exists(path):
        if local_file is not None:
            # an explicitly-passed file must never silently fall through
            # to different weights (e.g. a typoed fine-tune path loading
            # the packaged artifact instead)
            raise FileNotFoundError(f"local_file not found: {local_file}")
        builtin = BUILTIN_WEIGHTS.get((model_name, pretrained_type))
        if builtin is not None:
            rel, want = builtin
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "weights", rel)
            got = checksum(path)
            if got != want:
                raise IOError(f"builtin weights {rel} corrupt: adler32 "
                              f"{got} != {want}")
            # the caller's explicit pin applies on EVERY resolution path
            if expected_checksum is not None and got != expected_checksum:
                raise IOError(
                    f"checksum mismatch for builtin {rel}: expected "
                    f"{expected_checksum}, got {got}")
            return load_model(path)
        raise FileNotFoundError(
            f"no pretrained weights for '{model_name}' ({pretrained_type}) at "
            f"{path} — place the checkpoint zip there or pass local_file=/"
            "install_weights(). (This build is zero-egress: no download URLs; "
            f"builtins available: {sorted(BUILTIN_WEIGHTS)})")
    if expected_checksum is not None:
        got = checksum(path)
        if got != expected_checksum:
            if local_file is None:
                os.remove(path)  # evict corrupt cache entry, like the reference
            raise IOError(
                f"checksum mismatch for {path}: expected {expected_checksum}, "
                f"got {got}" + ("" if local_file else " (cached copy evicted)"))
    return load_model(path)


def init_pretrained_int8(model_name: str,
                         pretrained_type: str = PretrainedType.IMAGENET,
                         calibration_inputs=None,
                         expected_checksum=None,
                         cache_dir=None, local_file=None):
    """The zoo's int8 serving entry: ``init_pretrained`` + the
    calibration sweep + per-channel weight quantization in one step
    (ops/quantize.py).  ``calibration_inputs`` is an array or list of
    arrays of REPRESENTATIVE per-example inputs (leading batch axis) —
    activation scales are only as good as the sweep; there is no
    synthetic default here because zoo models ship with known input
    distributions and the caller has them.  Returns a ``QuantizedModel``
    ready for ``serving.Engine`` (already quantized — load() without
    ``quantize=``)."""
    from ..ops.quantize import quantize_model

    if calibration_inputs is None:
        raise ValueError(
            "init_pretrained_int8 needs calibration_inputs — a batch (or "
            "list of batches) of representative per-example inputs")
    net = init_pretrained(model_name, pretrained_type,
                          expected_checksum=expected_checksum,
                          cache_dir=cache_dir, local_file=local_file)
    return quantize_model(net, calibration_inputs)
