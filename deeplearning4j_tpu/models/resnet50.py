"""ResNet-50 — the flagship/benchmark model.

Reference: zoo/model/ResNet50.java:33 (identity/conv blocks :91-132, full
graphBuilder :173): conv7x7/2 → maxpool3x3/2 → 4 stages of bottleneck
blocks [3,4,6,3] → global average pool → softmax.  Built as a
ComputationGraph with ElementWiseVertex(add) residual connections, exactly
the reference's graph shape — but NHWC + fused XLA convs instead of
NCHW + im2col/cuDNN.
"""

from ..nn.conf.inputs import InputType
from ..nn.graph import ComputationGraph, ElementWiseVertex, GraphBuilder
from ..nn.layers import (
    ActivationLayer, BatchNormalization, Convolution2D, GlobalPooling, OutputLayer,
    Subsampling2D,
)
from ..nn.updaters import Adam


def _conv_bn(b: GraphBuilder, name: str, inp: str, n_out: int, kernel, stride,
             mode="same", act="relu") -> str:
    b.add_layer(f"{name}_conv", Convolution2D(n_out=n_out, kernel=kernel, stride=stride,
                                              convolution_mode=mode, activation="identity",
                                              has_bias=False), inp)
    b.add_layer(f"{name}_bn", BatchNormalization(activation=act), f"{name}_conv")
    return f"{name}_bn"


def _bottleneck(b: GraphBuilder, name: str, inp: str, filters, stride=1) -> str:
    """Bottleneck residual block (reference identity/conv block :91-132):
    1x1 reduce → 3x3 → 1x1 expand, projection shortcut when stride>1 or
    channel change."""
    f1, f2, f3 = filters
    x = _conv_bn(b, f"{name}_a", inp, f1, (1, 1), (stride, stride))
    x = _conv_bn(b, f"{name}_b", x, f2, (3, 3), (1, 1))
    x = _conv_bn(b, f"{name}_c", x, f3, (1, 1), (1, 1), act="identity")
    shortcut = inp
    if stride != 1 or name.endswith("block1"):
        shortcut = _conv_bn(b, f"{name}_sc", inp, f3, (1, 1), (stride, stride),
                            act="identity")
    b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, shortcut)
    b.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_relu"


def ResNet50(height: int = 224, width: int = 224, channels: int = 3,
             num_classes: int = 1000, seed: int = 42, updater=None) -> ComputationGraph:
    b = (GraphBuilder()
         .seed(seed)
         .updater(updater or Adam(lr=1e-3))
         .add_inputs("in")
         .set_input_types(**{"in": InputType.convolutional(height, width, channels)}))

    x = _conv_bn(b, "stem", "in", 64, (7, 7), (2, 2))
    b.add_layer("stem_pool", Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2),
                                           convolution_mode="same"), x)
    x = "stem_pool"

    stages = [
        ("stage1", [64, 64, 256], 3, 1),
        ("stage2", [128, 128, 512], 4, 2),
        ("stage3", [256, 256, 1024], 6, 2),
        ("stage4", [512, 512, 2048], 3, 2),
    ]
    for sname, filters, blocks, first_stride in stages:
        for i in range(1, blocks + 1):
            x = _bottleneck(b, f"{sname}_block{i}", x, filters,
                            stride=first_stride if i == 1 else 1)

    b.add_layer("avgpool", GlobalPooling(pooling="avg"), x)
    b.add_layer("out", OutputLayer(n_out=num_classes, activation="softmax",
                                   loss="mcxent"), "avgpool")
    b.set_outputs("out")
    net = ComputationGraph(b.build())
    net.init()
    return net
