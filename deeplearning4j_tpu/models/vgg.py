"""VGG16 / VGG19 — reference zoo/model/VGG16.java, VGG19.java
(Simonyan & Zisserman 2014 configurations D and E)."""

from ..nn.conf.inputs import InputType
from ..nn.layers import Convolution2D, Dense, OutputLayer, Subsampling2D
from ..nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from ..nn.updaters import Nesterovs


def _vgg(block_convs, height, width, channels, num_classes, seed, updater):
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Nesterovs(lr=1e-2, momentum=0.9)))
    for n_out, reps in block_convs:
        for _ in range(reps):
            b.layer(Convolution2D(n_out=n_out, kernel=(3, 3), convolution_mode="same",
                                  activation="relu"))
        b.layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(2, 2)))
    b.layer(Dense(n_out=4096, activation="relu", dropout=0.5))
    b.layer(Dense(n_out=4096, activation="relu", dropout=0.5))
    b.layer(OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"))
    b.set_input_type(InputType.convolutional(height, width, channels))
    net = MultiLayerNetwork(b.build())
    net.init()
    return net


def VGG16(height: int = 224, width: int = 224, channels: int = 3,
          num_classes: int = 1000, seed: int = 42, updater=None) -> MultiLayerNetwork:
    return _vgg([(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)],
                height, width, channels, num_classes, seed, updater)


def VGG19(height: int = 224, width: int = 224, channels: int = 3,
          num_classes: int = 1000, seed: int = 42, updater=None) -> MultiLayerNetwork:
    return _vgg([(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)],
                height, width, channels, num_classes, seed, updater)
