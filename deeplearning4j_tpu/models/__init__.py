"""Model zoo (replaces deeplearning4j-zoo, reference zoo/model/*).

Each zoo model is a function returning an initialized-config network
(MultiLayerNetwork or ComputationGraph), mirroring the reference's 12
instantiable architectures (zoo/ZooModel.java:23).  Pretrained-weight
loading hooks exist but no weights ship in-repo (zero-egress environment);
the checkpoint format is the framework zip.
"""

from .lenet import LeNet
from .simplecnn import SimpleCNN
from .alexnet import AlexNet
from .vgg import VGG16, VGG19
from .resnet50 import ResNet50
from .darknet19 import Darknet19
from .tinyyolo import TinyYOLO
from .textgen_lstm import TextGenerationLSTM
from .transformer import (TransformerLM, TransformerBlock,
                          PositionalEmbedding, TransformerDecodeAdapter)
from .googlenet import GoogLeNet
from .inception_resnet_v1 import InceptionResNetV1
from .facenet_nn4 import FaceNetNN4Small2
from .pretrained import (
    PretrainedType, cached_path, checksum, init_pretrained,
    init_pretrained_int8, install_weights,
)

ZOO = {
    "lenet": LeNet,
    "simplecnn": SimpleCNN,
    "alexnet": AlexNet,
    "vgg16": VGG16,
    "vgg19": VGG19,
    "resnet50": ResNet50,
    "darknet19": Darknet19,
    "tinyyolo": TinyYOLO,
    "textgenerationlstm": TextGenerationLSTM,
    "transformerlm": TransformerLM,
    "googlenet": GoogLeNet,
    "inceptionresnetv1": InceptionResNetV1,
    "facenetnn4small2": FaceNetNN4Small2,
}
