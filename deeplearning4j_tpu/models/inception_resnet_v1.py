"""Inception-ResNet-v1 (Szegedy et al. 2016) — the FaceNet backbone.

Reference: zoo/model/InceptionResNetV1.java (stem :88-120,
inception-resnet A/B/C blocks + reductions via FaceNetHelper, embedding
head :121-139: avgpool → dropout → dense 128 → L2 normalize → center-loss
softmax).  Residual branches are scaled before the add (the paper's
stabilization trick) via ScaleVertex.
"""

from ..nn.conf.inputs import InputType
from ..nn.graph import (
    ComputationGraph, ElementWiseVertex, GraphBuilder, L2NormalizeVertex,
    MergeVertex, ScaleVertex,
)
from ..nn.layers import (
    ActivationLayer, BatchNormalization, CenterLossOutputLayer, Convolution2D,
    Dense, DropoutLayer, GlobalPooling, Subsampling2D,
)
from ..nn.updaters import Adam


def _conv(b, name, inp, n_out, kernel, stride=(1, 1), mode="same", act="relu"):
    b.add_layer(name, Convolution2D(n_out=n_out, kernel=kernel, stride=stride,
                convolution_mode=mode, activation=act), inp)
    return name


def _res_block(b, name, inp, branches, n_channels, scale=0.17):
    """Inception-resnet block: parallel conv branches → 1x1 linear conv →
    scaled residual add → relu (InceptionResNetV1.java block builders)."""
    outs = []
    for bi, branch in enumerate(branches):
        x = inp
        for li, (n, k) in enumerate(branch):
            x = _conv(b, f"{name}_b{bi}_{li}", x, n, k)
        outs.append(x)
    if len(outs) > 1:
        b.add_vertex(f"{name}_cat", MergeVertex(), *outs)
        cat = f"{name}_cat"
    else:
        cat = outs[0]
    up = _conv(b, f"{name}_up", cat, n_channels, (1, 1), act="identity")
    b.add_vertex(f"{name}_scale", ScaleVertex(factor=scale), up)
    b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp, f"{name}_scale")
    b.add_layer(f"{name}", ActivationLayer(activation="relu"), f"{name}_add")
    return name


def InceptionResNetV1(height: int = 160, width: int = 160, channels: int = 3,
                      num_classes: int = 1000, embedding_size: int = 128,
                      a_blocks: int = 5, b_blocks: int = 10, c_blocks: int = 5,
                      updater=None) -> ComputationGraph:
    """Block counts default to the paper/reference (5x A, 10x B, 5x C)."""
    b = (GraphBuilder()
         .seed(12345)
         .updater(updater if updater is not None else Adam(lr=1e-3))
         .add_inputs("in")
         .set_input_types(**{"in": InputType.convolutional(height, width, channels)}))
    # stem (InceptionResNetV1.java:88-120)
    x = _conv(b, "stem1", "in", 32, (3, 3), (2, 2), mode="truncate")
    x = _conv(b, "stem2", x, 32, (3, 3), mode="truncate")
    x = _conv(b, "stem3", x, 64, (3, 3))
    b.add_layer("stem_pool", Subsampling2D(pooling="max", kernel=(3, 3),
                stride=(2, 2), convolution_mode="same"), x)
    x = _conv(b, "stem4", "stem_pool", 80, (1, 1))
    x = _conv(b, "stem5", x, 192, (3, 3), mode="truncate")
    x = _conv(b, "stem6", x, 256, (3, 3), (2, 2), mode="same")
    # inception-resnet-A (block35): branches 1x1 / 1x1-3x3 / 1x1-3x3-3x3
    for i in range(a_blocks):
        x = _res_block(b, f"a{i}", x,
                       [[(32, (1, 1))],
                        [(32, (1, 1)), (32, (3, 3))],
                        [(32, (1, 1)), (32, (3, 3)), (32, (3, 3))]],
                       n_channels=256, scale=0.17)
    # reduction-A: 3x3/2 conv + 1x1-3x3-3x3/2 + maxpool
    _conv(b, "redA_b0", x, 384, (3, 3), (2, 2), mode="same")
    _conv(b, "redA_b1a", x, 192, (1, 1))
    _conv(b, "redA_b1b", "redA_b1a", 192, (3, 3))
    _conv(b, "redA_b1c", "redA_b1b", 256, (3, 3), (2, 2), mode="same")
    b.add_layer("redA_pool", Subsampling2D(pooling="max", kernel=(3, 3),
                stride=(2, 2), convolution_mode="same"), x)
    b.add_vertex("redA", MergeVertex(), "redA_b0", "redA_b1c", "redA_pool")
    x = "redA"
    # inception-resnet-B (block17): 1x1 / 1x1-1x7-7x1
    for i in range(b_blocks):
        x = _res_block(b, f"b{i}", x,
                       [[(128, (1, 1))],
                        [(128, (1, 1)), (128, (1, 7)), (128, (7, 1))]],
                       n_channels=896, scale=0.10)
    # reduction-B
    _conv(b, "redB_b0a", x, 256, (1, 1))
    _conv(b, "redB_b0b", "redB_b0a", 384, (3, 3), (2, 2), mode="same")
    _conv(b, "redB_b1a", x, 256, (1, 1))
    _conv(b, "redB_b1b", "redB_b1a", 256, (3, 3), (2, 2), mode="same")
    _conv(b, "redB_b2a", x, 256, (1, 1))
    _conv(b, "redB_b2b", "redB_b2a", 256, (3, 3))
    _conv(b, "redB_b2c", "redB_b2b", 256, (3, 3), (2, 2), mode="same")
    b.add_layer("redB_pool", Subsampling2D(pooling="max", kernel=(3, 3),
                stride=(2, 2), convolution_mode="same"), x)
    b.add_vertex("redB", MergeVertex(), "redB_b0b", "redB_b1b", "redB_b2c", "redB_pool")
    x = "redB"
    # inception-resnet-C (block8): 1x1 / 1x1-1x3-3x1
    for i in range(c_blocks):
        x = _res_block(b, f"c{i}", x,
                       [[(192, (1, 1))],
                        [(192, (1, 1)), (192, (1, 3)), (192, (3, 1))]],
                       n_channels=1792, scale=0.20)
    # embedding head (:121-139)
    b.add_layer("gap", GlobalPooling(pooling="avg"), x)
    b.add_layer("drop", DropoutLayer(dropout=0.2), "gap")
    b.add_layer("bottleneck", Dense(n_out=embedding_size, activation="identity"),
                "drop")
    b.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
    b.add_layer("out", CenterLossOutputLayer(n_out=num_classes,
                                             activation="softmax"), "embeddings")
    b.set_outputs("out")
    return ComputationGraph(b.build())
