"""TextGenerationLSTM — reference zoo/model/TextGenerationLSTM.java
(char-RNN: 2×LSTM(256) + RnnOutputLayer, Karpathy-style)."""

from ..nn.conf.inputs import InputType
from ..nn.layers import GravesLSTM, RnnOutputLayer
from ..nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from ..nn.updaters import RmsProp
from ..nn.updaters import GradientNormalization


def TextGenerationLSTM(vocab_size: int = 77, hidden: int = 256,
                       tbptt_length: int = 50, seed: int = 42,
                       updater=None) -> MultiLayerNetwork:
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or RmsProp(lr=1e-2))
         .gradient_normalization(GradientNormalization.CLIP_ELEMENTWISE_ABSOLUTE, 1.0)
         .layer(GravesLSTM(n_out=hidden))
         .layer(GravesLSTM(n_out=hidden))
         .layer(RnnOutputLayer(n_out=vocab_size, activation="softmax", loss="mcxent"))
         .tbptt(tbptt_length)
         .set_input_type(InputType.recurrent(vocab_size)))
    net = MultiLayerNetwork(b.build())
    net.init()
    return net
