"""LeNet — reference zoo/model/LeNet.java (conv5x5 → pool → conv5x5 → pool
→ dense 500 → softmax, the dl4j-zoo variant)."""

from ..nn.conf.inputs import InputType
from ..nn.layers import Convolution2D, Dense, OutputLayer, Subsampling2D
from ..nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from ..nn.updaters import Adam


def LeNet(height: int = 28, width: int = 28, channels: int = 1,
          num_classes: int = 10, seed: int = 123, updater=None) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Adam(lr=1e-3))
            .layer(Convolution2D(n_out=20, kernel=(5, 5), stride=(1, 1),
                                 activation="identity", convolution_mode="same"))
            .layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(2, 2)))
            .layer(Convolution2D(n_out=50, kernel=(5, 5), stride=(1, 1),
                                 activation="identity", convolution_mode="same"))
            .layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(2, 2)))
            .layer(Dense(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(height, width, channels))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net
