"""SimpleCNN — reference zoo/model/SimpleCNN.java (4 conv blocks + dropout
head, designed for small imagery)."""

from ..nn.conf.inputs import InputType
from ..nn.layers import BatchNormalization, Convolution2D, Dense, OutputLayer, Subsampling2D
from ..nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from ..nn.updaters import Adam


def SimpleCNN(height: int = 48, width: int = 48, channels: int = 3,
              num_classes: int = 10, seed: int = 123, updater=None) -> MultiLayerNetwork:
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Adam(lr=1e-3)))
    for n_out in (16, 32, 64, 128):
        b.layer(Convolution2D(n_out=n_out, kernel=(3, 3), activation="relu",
                              convolution_mode="same"))
        b.layer(BatchNormalization())
        b.layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(2, 2)))
    b.layer(Dense(n_out=256, activation="relu", dropout=0.5))
    b.layer(OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"))
    b.set_input_type(InputType.convolutional(height, width, channels))
    net = MultiLayerNetwork(b.build())
    net.init()
    return net
