"""TinyYOLO — reference zoo/model/TinyYOLO.java (tiny YOLOv2: 9 conv layers
+ Yolo2OutputLayer, anchors from the VOC config)."""

from ..nn.conf.inputs import InputType
from ..nn.layers import BatchNormalization, Convolution2D, Subsampling2D, Yolo2OutputLayer
from ..nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from ..nn.updaters import Adam

_DEFAULT_ANCHORS = [[1.08, 1.19], [3.42, 4.41], [6.63, 11.38], [9.42, 5.11], [16.62, 10.52]]


def TinyYOLO(height: int = 416, width: int = 416, channels: int = 3,
             num_classes: int = 20, anchors=None, seed: int = 42,
             updater=None) -> MultiLayerNetwork:
    anchors = anchors if anchors is not None else _DEFAULT_ANCHORS
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Adam(lr=1e-3)))
    for i, n_out in enumerate((16, 32, 64, 128, 256, 512)):
        b.layer(Convolution2D(n_out=n_out, kernel=(3, 3), convolution_mode="same",
                              activation="identity", has_bias=False))
        b.layer(BatchNormalization(activation="leakyrelu"))
        # last pool is stride 1 (reference TinyYOLO: 416→13 with 5 /2 pools)
        stride = 2 if i < 5 else 1
        b.layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(stride, stride),
                              convolution_mode="same"))
    for n_out in (1024, 1024):
        b.layer(Convolution2D(n_out=n_out, kernel=(3, 3), convolution_mode="same",
                              activation="identity", has_bias=False))
        b.layer(BatchNormalization(activation="leakyrelu"))
    n_boxes = len(anchors)
    b.layer(Convolution2D(n_out=n_boxes * (5 + num_classes), kernel=(1, 1),
                          convolution_mode="same", activation="identity"))
    b.layer(Yolo2OutputLayer(anchors=anchors, n_classes=num_classes))
    b.set_input_type(InputType.convolutional(height, width, channels))
    net = MultiLayerNetwork(b.build())
    net.init()
    return net
