"""Darknet19 — reference zoo/model/Darknet19.java (YOLOv2 backbone:
19 conv layers, BN + leaky-relu, 5 maxpools)."""

from ..nn.conf.inputs import InputType
from ..nn.layers import BatchNormalization, Convolution2D, GlobalPooling, OutputLayer, Subsampling2D
from ..nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from ..nn.updaters import Nesterovs


def _cbl(b, n_out, kernel=(3, 3)):
    b.layer(Convolution2D(n_out=n_out, kernel=kernel, convolution_mode="same",
                          activation="identity", has_bias=False))
    b.layer(BatchNormalization(activation="leakyrelu"))


def Darknet19(height: int = 224, width: int = 224, channels: int = 3,
              num_classes: int = 1000, seed: int = 42, updater=None) -> MultiLayerNetwork:
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Nesterovs(lr=1e-3, momentum=0.9)))
    _cbl(b, 32)
    b.layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(2, 2)))
    _cbl(b, 64)
    b.layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(2, 2)))
    _cbl(b, 128); _cbl(b, 64, (1, 1)); _cbl(b, 128)
    b.layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(2, 2)))
    _cbl(b, 256); _cbl(b, 128, (1, 1)); _cbl(b, 256)
    b.layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(2, 2)))
    _cbl(b, 512); _cbl(b, 256, (1, 1)); _cbl(b, 512); _cbl(b, 256, (1, 1)); _cbl(b, 512)
    b.layer(Subsampling2D(pooling="max", kernel=(2, 2), stride=(2, 2)))
    _cbl(b, 1024); _cbl(b, 512, (1, 1)); _cbl(b, 1024); _cbl(b, 512, (1, 1)); _cbl(b, 1024)
    b.layer(Convolution2D(n_out=num_classes, kernel=(1, 1), convolution_mode="same",
                          activation="identity"))
    b.layer(GlobalPooling(pooling="avg"))
    b.layer(OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"))
    b.set_input_type(InputType.convolutional(height, width, channels))
    net = MultiLayerNetwork(b.build())
    net.init()
    return net
