"""TransformerLM — the long-context flagship (no reference analog).

DL4J 0.9.2's sequence flagship is TextGenerationLSTM
(zoo/model/TextGenerationLSTM.java); the TPU framework adds a decoder-only
transformer LM as the model that exercises every modern axis the SURVEY
mandates (§2.3/§5): flash attention (pallas), ring attention over ``seq``,
tensor-parallel FFN/heads over ``model``, and a GPipe pipeline over
``pipe`` (parallel/transformer.py drives the 4D-parallel train step).

``block_params``/``block_apply`` are the single source of truth for the
block math — the TransformerBlock layer (single-chip MLN path) and the
ShardedTransformerLM (multi-chip path) both call them, so parity between
the two is structural rather than tested-for.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..nn.conf.inputs import InputType
from ..nn.layers import EmbeddingSequence, RnnOutputLayer
from ..nn.layers.base import Array, ForwardOut, Layer, register_layer
from ..nn.layers.normalization import layer_norm
from ..nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from ..nn.updaters import Adam, GradientNormalization
from ..ops.attention import flash_mha, merge_heads, mha, split_heads
from ..ops.initializers import init_weight


def block_params(rng: Array, d_model: int, n_heads: int, d_ff: int,
                 dtype=jnp.float32, weight_init: str = "xavier") -> Dict[str, Array]:
    """One pre-LN transformer block's parameter tree."""
    kq, kk, kv, ko, k1, k2 = jax.random.split(rng, 6)
    d = d_model
    return {
        "ln1_g": jnp.ones((d,), dtype), "ln1_b": jnp.zeros((d,), dtype),
        "Wq": init_weight(kq, (d, d), weight_init, d, d, dtype),
        "Wk": init_weight(kk, (d, d), weight_init, d, d, dtype),
        "Wv": init_weight(kv, (d, d), weight_init, d, d, dtype),
        "Wo": init_weight(ko, (d, d), weight_init, d, d, dtype),
        "bo": jnp.zeros((d,), dtype),
        "ln2_g": jnp.ones((d,), dtype), "ln2_b": jnp.zeros((d,), dtype),
        "W1": init_weight(k1, (d, d_ff), weight_init, d, d_ff, dtype),
        "b1": jnp.zeros((d_ff,), dtype),
        "W2": init_weight(k2, (d_ff, d), weight_init, d_ff, d, dtype),
        "b2": jnp.zeros((d,), dtype),
    }


def block_apply(p: Dict[str, Array], h: Array, n_heads: int, *,
                causal: bool = True,
                attention_fn: Optional[Callable] = None,
                psum_axis: Optional[str] = None) -> Array:
    """Pre-LN block: h + attn(LN(h)); h + FFN(LN(h)).

    ``attention_fn(q, k, v)`` defaults to the pallas flash kernel; the
    sharded trainer passes ring attention over the ``seq`` axis instead.
    ``psum_axis``: when the projections are tensor-parallel (heads/FFN
    columns sharded), the row-parallel Wo/W2 matmuls are followed by a psum
    over that axis (set by the shard_map caller; None = single device).
    """
    def maybe_psum(x):
        return jax.lax.psum(x, psum_axis) if psum_axis else x

    u = layer_norm(h, p["ln1_g"], p["ln1_b"])
    q = split_heads(u @ p["Wq"], n_heads)
    k = split_heads(u @ p["Wk"], n_heads)
    v = split_heads(u @ p["Wv"], n_heads)
    if attention_fn is None:
        attention_fn = lambda q, k, v: flash_mha(q, k, v, causal)
    att = maybe_psum(merge_heads(attention_fn(q, k, v)) @ p["Wo"]) + p["bo"]
    h = h + att
    u = layer_norm(h, p["ln2_g"], p["ln2_b"])
    f = jax.nn.gelu(u @ p["W1"] + p["b1"])
    h = h + maybe_psum(f @ p["W2"]) + p["b2"]
    return h


def block_kv_project(p: Dict[str, Array], h: Array,
                     n_heads: int) -> tuple:
    """First half of the pre-LN block, split out for the decode path
    (serving/decode.py): q/k/v head projections of LN(h), so the caller
    can write k/v into the paged cache BEFORE attention runs against the
    gathered full-length view (ops/kv_cache.py).  Returns (q, k, v) as
    [B, H, T, d_head]."""
    u = layer_norm(h, p["ln1_g"], p["ln1_b"])
    return (split_heads(u @ p["Wq"], n_heads),
            split_heads(u @ p["Wk"], n_heads),
            split_heads(u @ p["Wv"], n_heads))


def block_finish(p: Dict[str, Array], h: Array, att_heads: Array, *,
                 psum_axis: Optional[str] = None) -> Array:
    """Second half of the pre-LN block: output projection + residual +
    FFN.  Same math as the tail of ``block_apply``; the decode
    prefill/step/re-encode paths all share it so their per-position
    bits agree by construction.  ``psum_axis``: the tensor-parallel
    decode path (parallel/transformer.py) passes ``att_heads`` holding
    only the LOCAL head group and a row-slice of ``Wo`` in ``p`` — the
    partial output projections psum over that axis before bias +
    residual.  Every shard runs the identical psum, so the per-shard
    decode-vs-reencode bit contract holds layout-for-layout."""
    m = merge_heads(att_heads) @ p["Wo"]
    if psum_axis is not None:
        m = jax.lax.psum(m, psum_axis)
    h = h + (m + p["bo"])
    u = layer_norm(h, p["ln2_g"], p["ln2_b"])
    f = jax.nn.gelu(u @ p["W1"] + p["b1"])
    return h + f @ p["W2"] + p["b2"]


@register_layer
@dataclasses.dataclass
class TransformerBlock(Layer):
    """Pre-LN decoder block as a single MLN layer [B,T,D] → [B,T,D].

    Homogeneous by construction, so N of these stack into the pipeline's
    stage axis (parallel/pipeline.py) without any repartitioning.
    """

    d_model: int = 0
    n_heads: int = 8
    d_ff: int = 0              # 0 → 4*d_model
    causal: bool = True
    kernel: str = "flash"      # "flash" | "xla"

    wants = "rnn"

    def infer_nin(self, in_type: InputType) -> None:
        if not self.d_model:
            self.d_model = in_type.size
        if not self.d_ff:
            self.d_ff = 4 * self.d_model

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.recurrent(self.d_model, in_type.timesteps)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return block_params(rng, self.d_model, self.n_heads,
                            self.d_ff or 4 * self.d_model, dtype, self._winit())

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        if mask is not None or self.kernel == "xla":
            att_mask = mask[:, None, None, :] if mask is not None else None
            attention_fn = lambda q, k, v: mha(q, k, v, causal=self.causal,
                                               mask=att_mask)
        else:
            attention_fn = None
        y = block_apply(params, x, self.n_heads, causal=self.causal,
                        attention_fn=attention_fn)
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return ForwardOut(y, state, mask)


@register_layer
@dataclasses.dataclass
class PositionalEmbedding(Layer):
    """Learned absolute positions added to the sequence embedding."""

    max_len: int = 512
    d_model: int = 0

    wants = "rnn"

    def infer_nin(self, in_type: InputType) -> None:
        if not self.d_model:
            self.d_model = in_type.size

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return {"P": 0.02 * jax.random.normal(rng, (self.max_len, self.d_model),
                                              dtype)}

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        t = x.shape[1]
        return ForwardOut(x + params["P"][:t].astype(x.dtype), state, mask)


def TransformerLM(vocab_size: int = 256, n_layers: int = 4, d_model: int = 256,
                  n_heads: int = 8, d_ff: int = 0, max_len: int = 512,
                  seed: int = 42, updater=None, kernel: str = "flash",
                  dtype=None) -> MultiLayerNetwork:
    """Decoder-only LM: EmbeddingSequence + positions + N blocks + head."""
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Adam(lr=3e-4))
         .gradient_normalization(GradientNormalization.CLIP_L2_PER_LAYER, 1.0)
         .layer(EmbeddingSequence(n_in=vocab_size, n_out=d_model))
         .layer(PositionalEmbedding(max_len=max_len, d_model=d_model)))
    for _ in range(n_layers):
        b.layer(TransformerBlock(d_model=d_model, n_heads=n_heads, d_ff=d_ff,
                                 kernel=kernel))
    b.layer(RnnOutputLayer(n_out=vocab_size, activation="softmax", loss="mcxent"))
    b.set_input_type(InputType.recurrent(vocab_size, max_len))
    if dtype is not None:
        b.dtype(*dtype) if isinstance(dtype, tuple) else b.dtype(dtype)
    net = MultiLayerNetwork(b.build())
    net.init()
    return net


class TransformerDecodeAdapter:
    """Serve a single-chip ``TransformerLM`` MultiLayerNetwork through
    ``serving.DecodeEngine``: the same ``params`` + ``decode_program()``
    surface ShardedTransformerLM exposes, built from the MLN layer stack
    (EmbeddingSequence, PositionalEmbedding, TransformerBlock × N,
    RnnOutputLayer).  The program's three closures (prefill / step /
    re-encode) share every per-position op — embedding lookup, position
    add, block_kv_project/block_finish, the pre-softmax head — and
    ops/kv_cache.det_attention, so incremental logits are BIT-identical
    to re-encoding the same tokens.  The wrapped network itself is
    untouched: its one-shot ``output``/``predict`` path keeps its own
    jit programs (the no-behavior-change regression in
    tests/test_decode.py)."""

    def __init__(self, net: MultiLayerNetwork):
        layers = net.conf.layers
        ok = (len(layers) >= 4
              and isinstance(layers[0], EmbeddingSequence)
              and isinstance(layers[1], PositionalEmbedding)
              and all(isinstance(l, TransformerBlock) for l in layers[2:-1])
              and isinstance(layers[-1], RnnOutputLayer))
        if not ok:
            raise ValueError(
                "TransformerDecodeAdapter needs the TransformerLM stack "
                "(EmbeddingSequence, PositionalEmbedding, TransformerBlock "
                "x N, RnnOutputLayer); got "
                + ", ".join(type(l).__name__ for l in layers))
        cd = getattr(net.conf, "compute_dtype", None)
        if cd is not None and jnp.dtype(cd) != jnp.float32:
            raise NotImplementedError(
                "decode serves the f32 params path; compute_dtype "
                f"{cd!r} would break the re-encode bit-identity contract")
        self.net = net
        self._embed_lay = layers[0]
        self._out_lay = layers[-1]
        self._n_blocks = len(layers) - 3
        self.n_heads = int(layers[2].n_heads)
        self.vocab_size = int(self._out_lay.n_out)
        self.params = {
            "embed": net.params[0], "pos": net.params[1],
            "blocks": [net.params[2 + i] for i in range(self._n_blocks)],
            "head": net.params[len(layers) - 1],
        }

    def decode_program(self, page_size: int = 16,
                       max_len: Optional[int] = None):
        from ..ops.kv_cache import (
            NEG_INF, DecodeProgram, det_attention, gather_layer,
            write_prefill, write_step, write_tokens,
        )
        from ..ops.sampling import sample_token

        pos_rows = int(self.params["pos"]["P"].shape[0])
        if max_len is None:
            max_len = (pos_rows // page_size) * page_size
        if max_len % page_size or not (0 < max_len <= pos_rows):
            raise ValueError(
                f"max_len {max_len} must be a positive multiple of "
                f"page_size {page_size} and <= the position table "
                f"({pos_rows})")
        L = int(max_len)
        n_heads = self.n_heads
        n_layers = self._n_blocks
        embed_lay, out_lay = self._embed_lay, self._out_lay
        d_model = int(self.params["embed"]["W"].shape[1])

        def tok_embed(params, idx):
            y = params["embed"]["W"][idx]
            if embed_lay.has_bias:
                y = y + params["embed"]["b"]
            return embed_lay._act(y)

        def head(params, h):
            y = h @ params["head"]["W"]
            if out_lay.has_bias:
                y = y + params["head"]["b"]
            return y          # pre-softmax logits (RnnOutputLayer._pre)

        def prefill(params, k_pages, v_pages, page_table_row, tokens, n_real):
            tb = tokens.shape[0]
            h = (tok_embed(params, tokens) + params["pos"]["P"][:tb])[None]
            bias = jnp.where(
                jnp.arange(L, dtype=jnp.int32)[None, :]
                <= jnp.arange(tb, dtype=jnp.int32)[:, None],
                0.0, NEG_INF)[None, None]
            pt = page_table_row[None]
            for i, bp in enumerate(params["blocks"]):
                q, k, v = block_kv_project(bp, h, n_heads)
                k_pages = write_prefill(k_pages, i, page_table_row,
                                        k.transpose(0, 2, 1, 3)[0])
                v_pages = write_prefill(v_pages, i, page_table_row,
                                        v.transpose(0, 2, 1, 3)[0])
                k_all = gather_layer(k_pages, i, pt).transpose(0, 2, 1, 3)
                v_all = gather_layer(v_pages, i, pt).transpose(0, 2, 1, 3)
                h = block_finish(bp, h, det_attention(q, k_all, v_all, bias))
            return k_pages, v_pages, head(params, h)[0, n_real - 1]

        def step(params, k_pages, v_pages, page_table, tokens, positions,
                 active):
            h = (tok_embed(params, tokens)
                 + params["pos"]["P"][positions])[:, None]
            bias = jnp.where(
                jnp.arange(L, dtype=jnp.int32)[None, :]
                <= positions[:, None], 0.0, NEG_INF)[:, None, None, :]
            pt = jnp.where(active[:, None], page_table, 0)
            for i, bp in enumerate(params["blocks"]):
                q, k, v = block_kv_project(bp, h, n_heads)
                k_pages = write_step(k_pages, i, pt, positions, k[:, :, 0])
                v_pages = write_step(v_pages, i, pt, positions, v[:, :, 0])
                k_all = gather_layer(k_pages, i, pt).transpose(0, 2, 1, 3)
                v_all = gather_layer(v_pages, i, pt).transpose(0, 2, 1, 3)
                h = block_finish(bp, h, det_attention(q, k_all, v_all, bias))
            return k_pages, v_pages, head(params, h)[:, 0]

        def prefill_at(params, k_pages, v_pages, page_table_row, tokens,
                       n_real, offset):
            # suffix prefill for a prefix-cache hit: rows occupy absolute
            # positions offset..offset+tb-1 and attend over the shared
            # prefix rows already resident in the attached pages.  Same
            # per-row ops as prefill, so logits stay bit-identical.
            tb = tokens.shape[0]
            pos_abs = offset + jnp.arange(tb, dtype=jnp.int32)
            h = (tok_embed(params, tokens)
                 + params["pos"]["P"][jnp.clip(pos_abs, 0, pos_rows - 1)]
                 )[None]
            bias = jnp.where(
                jnp.arange(L, dtype=jnp.int32)[None, :]
                <= pos_abs[:, None], 0.0, NEG_INF)[None, None]
            pt = page_table_row[None]
            for i, bp in enumerate(params["blocks"]):
                q, k, v = block_kv_project(bp, h, n_heads)
                k_pages = write_prefill(k_pages, i, page_table_row,
                                        k.transpose(0, 2, 1, 3)[0], offset)
                v_pages = write_prefill(v_pages, i, page_table_row,
                                        v.transpose(0, 2, 1, 3)[0], offset)
                k_all = gather_layer(k_pages, i, pt).transpose(0, 2, 1, 3)
                v_all = gather_layer(v_pages, i, pt).transpose(0, 2, 1, 3)
                h = block_finish(bp, h, det_attention(q, k_all, v_all, bias))
            return k_pages, v_pages, head(params, h)[0, n_real - 1]

        def spec_step(params, k_pages, v_pages, page_table, tokens,
                      positions, active):
            # speculative verify: score tokens [S, T] at absolute
            # positions positions[s]..positions[s]+T-1 in ONE call,
            # writing their K/V rows (overflow rows route to scratch in
            # write_tokens).  Rejected rows are garbage-but-finite and
            # stay masked until overwritten by the next round.
            s_n, t_n = tokens.shape
            pos_abs = positions[:, None] + jnp.arange(t_n, dtype=jnp.int32)
            h = (tok_embed(params, tokens)
                 + params["pos"]["P"][jnp.clip(pos_abs, 0, pos_rows - 1)])
            bias = jnp.where(
                jnp.arange(L, dtype=jnp.int32)[None, None, :]
                <= pos_abs[:, :, None], 0.0, NEG_INF)[:, None]
            pt = jnp.where(active[:, None], page_table, 0)
            for i, bp in enumerate(params["blocks"]):
                q, k, v = block_kv_project(bp, h, n_heads)
                k_pages = write_tokens(k_pages, i, pt, positions,
                                       k.transpose(0, 2, 1, 3))
                v_pages = write_tokens(v_pages, i, pt, positions,
                                       v.transpose(0, 2, 1, 3))
                k_all = gather_layer(k_pages, i, pt).transpose(0, 2, 1, 3)
                v_all = gather_layer(v_pages, i, pt).transpose(0, 2, 1, 3)
                h = block_finish(bp, h, det_attention(q, k_all, v_all, bias))
            return k_pages, v_pages, head(params, h)

        vocab = self.vocab_size

        def step_multi(params, k_pages, v_pages, page_table, tokens,
                       positions, active, temps, top_ks, top_ps, seeds,
                       steps, budgets, eos_id, horizon):
            # H = horizon.shape[0] consecutive decode steps in ONE
            # program: scan of the step body with device-resident
            # sampling.  A slot that hits EOS / its token budget /
            # non-finite logits drops out of ``alive``; its page-table
            # row zeroes, so the remaining iterations write to scratch
            # and live slots' bits match H plain steps exactly.
            def body(carry, j):
                k_pages, v_pages, tok, alive = carry
                pos_j = positions + j
                h = (tok_embed(params, tok)
                     + params["pos"]["P"][jnp.clip(pos_j, 0, pos_rows - 1)]
                     )[:, None]
                bias = jnp.where(
                    jnp.arange(L, dtype=jnp.int32)[None, :]
                    <= pos_j[:, None], 0.0, NEG_INF)[:, None, None, :]
                pt = jnp.where(alive[:, None], page_table, 0)
                for i, bp in enumerate(params["blocks"]):
                    q, k, v = block_kv_project(bp, h, n_heads)
                    k_pages = write_step(k_pages, i, pt, pos_j, k[:, :, 0])
                    v_pages = write_step(v_pages, i, pt, pos_j, v[:, :, 0])
                    k_all = gather_layer(k_pages, i, pt).transpose(0, 2, 1, 3)
                    v_all = gather_layer(v_pages, i, pt).transpose(0, 2, 1, 3)
                    h = block_finish(bp, h,
                                     det_attention(q, k_all, v_all, bias))
                lgs = head(params, h)[:, 0]
                nxt, fin = jax.vmap(
                    lambda l, t, kk, pp, sd, st:
                        sample_token(l, t, kk, pp, sd, st, vocab)
                )(lgs, temps, top_ks, top_ps, seeds, steps + j)
                alive = (alive & fin & (nxt != eos_id)
                         & (j + 1 < budgets))
                return (k_pages, v_pages, nxt, alive), (nxt, fin, lgs)

            (k_pages, v_pages, _, _), (toks, fins, lgs) = jax.lax.scan(
                body, (k_pages, v_pages, tokens, active), horizon)
            return k_pages, v_pages, toks, fins, lgs

        def reencode(params, tokens):
            b, t = tokens.shape
            h = tok_embed(params, tokens) + params["pos"]["P"][:t]
            bias = jnp.where(
                jnp.arange(t, dtype=jnp.int32)[None, :]
                <= jnp.arange(t, dtype=jnp.int32)[:, None],
                0.0, NEG_INF)[None, None]
            for bp in params["blocks"]:
                q, k, v = block_kv_project(bp, h, n_heads)
                h = block_finish(bp, h, det_attention(q, k, v, bias))
            return head(params, h)

        return DecodeProgram(
            prefill=prefill, step=step, reencode=reencode,
            n_layers=n_layers, n_heads=n_heads, d_head=d_model // n_heads,
            vocab_size=self.vocab_size, max_len=L, page_size=page_size,
            pages_per_slot=L // page_size,
            prefill_at=prefill_at, spec_step=spec_step,
            step_multi=step_multi)
