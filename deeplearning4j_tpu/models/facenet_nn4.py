"""FaceNet NN4-small2 (Schroff et al. 2015, OpenFace variant).

Reference: zoo/model/FaceNetNN4Small2.java (:78-220: conv stem, inception
3a/3b/3c/4a/4e/5a/5b modules via FaceNetHelper.inception — branches with
3x3 and 5x5 reductions, L2 (p-norm) pooling projections — then avgpool →
dense 128 embedding → L2 normalize → center-loss softmax)."""

from ..nn.conf.inputs import InputType
from ..nn.graph import (
    ComputationGraph, GraphBuilder, L2NormalizeVertex, MergeVertex,
)
from ..nn.layers import (
    BatchNormalization, CenterLossOutputLayer, Convolution2D, Dense,
    GlobalPooling, LocalResponseNormalization, Subsampling2D,
)
from ..nn.updaters import Adam


def _conv(b, name, inp, n_out, kernel, stride=(1, 1), act="relu"):
    b.add_layer(name, Convolution2D(n_out=n_out, kernel=kernel, stride=stride,
                convolution_mode="same", activation=act), inp)
    return name


def _inception(b, name, inp, r3, n3, s3, r5, n5, pool_kind, pp):
    """FaceNetHelper.inception: 1x1→3x3 (+stride s3), optional 1x1→5x5,
    plus a pool branch — projected through 1x1 when pp>0, merged BARE when
    pp=0 (reference FaceNetNN4Small2.java:151-184 merges the unprojected
    max-pool into 3c/4e, so those modules' channel counts include the
    incoming channels)."""
    outs = []
    x = _conv(b, f"{name}_3x3r", inp, r3, (1, 1))
    outs.append(_conv(b, f"{name}_3x3", x, n3, (3, 3), (s3, s3)))
    if n5 > 0:
        x = _conv(b, f"{name}_5x5r", inp, r5, (1, 1))
        outs.append(_conv(b, f"{name}_5x5", x, n5, (5, 5), (s3, s3)))
    b.add_layer(f"{name}_pool", Subsampling2D(
        pooling=pool_kind, pnorm=2, kernel=(3, 3), stride=(s3, s3),
        convolution_mode="same"), inp)
    if pp > 0:
        outs.append(_conv(b, f"{name}_poolp", f"{name}_pool", pp, (1, 1)))
    else:
        outs.append(f"{name}_pool")
    b.add_vertex(name, MergeVertex(), *outs)
    return name


def FaceNetNN4Small2(height: int = 96, width: int = 96, channels: int = 3,
                     num_classes: int = 1000, embedding_size: int = 128,
                     updater=None) -> ComputationGraph:
    b = (GraphBuilder()
         .seed(12345)
         .updater(updater if updater is not None else Adam(lr=1e-3))
         .add_inputs("in")
         .set_input_types(**{"in": InputType.convolutional(height, width, channels)}))
    # stem (FaceNetNN4Small2.java:78-110)
    x = _conv(b, "conv1", "in", 64, (7, 7), (2, 2))
    b.add_layer("bn1", BatchNormalization(activation="relu"), x)
    b.add_layer("pool1", Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2),
                convolution_mode="same"), "bn1")
    b.add_layer("lrn1", LocalResponseNormalization(), "pool1")
    x = _conv(b, "conv2", "lrn1", 64, (1, 1))
    x = _conv(b, "conv3", x, 192, (3, 3))
    b.add_layer("bn3", BatchNormalization(activation="relu"), x)
    b.add_layer("lrn2", LocalResponseNormalization(), "bn3")
    b.add_layer("pool2", Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2),
                convolution_mode="same"), "lrn2")
    # inception stack (:111-200); (r3, n3, stride, r5, n5, pool, proj)
    x = _inception(b, "3a", "pool2", 96, 128, 1, 16, 32, "max", 32)
    x = _inception(b, "3b", x, 96, 128, 1, 32, 64, "pnorm", 64)
    x = _inception(b, "3c", x, 128, 256, 2, 32, 64, "max", 0)
    x = _inception(b, "4a", x, 96, 192, 1, 32, 64, "pnorm", 128)
    x = _inception(b, "4e", x, 160, 256, 2, 64, 128, "max", 0)
    x = _inception(b, "5a", x, 96, 384, 1, 0, 0, "pnorm", 96)
    x = _inception(b, "5b", x, 96, 384, 1, 0, 0, "max", 96)
    # embedding head (:200-220)
    b.add_layer("gap", GlobalPooling(pooling="avg"), x)
    b.add_layer("bottleneck", Dense(n_out=embedding_size, activation="identity"),
                "gap")
    b.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
    b.add_layer("out", CenterLossOutputLayer(n_out=num_classes,
                                             activation="softmax"), "embeddings")
    b.set_outputs("out")
    return ComputationGraph(b.build())
