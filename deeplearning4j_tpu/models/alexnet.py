"""AlexNet — reference zoo/model/AlexNet.java (Krizhevsky 2012 with LRN;
the dl4j-zoo one-tower variant)."""

from ..nn.conf.inputs import InputType
from ..nn.layers import (
    Convolution2D, Dense, LocalResponseNormalization, OutputLayer, Subsampling2D,
)
from ..nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from ..nn.updaters import Nesterovs


def AlexNet(height: int = 224, width: int = 224, channels: int = 3,
            num_classes: int = 1000, seed: int = 42, updater=None) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Nesterovs(lr=1e-2, momentum=0.9))
            .layer(Convolution2D(n_out=96, kernel=(11, 11), stride=(4, 4),
                                 activation="relu"))
            .layer(LocalResponseNormalization())
            .layer(Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2)))
            .layer(Convolution2D(n_out=256, kernel=(5, 5), convolution_mode="same",
                                 activation="relu", bias_init=1.0))
            .layer(LocalResponseNormalization())
            .layer(Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2)))
            .layer(Convolution2D(n_out=384, kernel=(3, 3), convolution_mode="same",
                                 activation="relu"))
            .layer(Convolution2D(n_out=384, kernel=(3, 3), convolution_mode="same",
                                 activation="relu", bias_init=1.0))
            .layer(Convolution2D(n_out=256, kernel=(3, 3), convolution_mode="same",
                                 activation="relu", bias_init=1.0))
            .layer(Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2)))
            .layer(Dense(n_out=4096, activation="relu", dropout=0.5, bias_init=1.0))
            .layer(Dense(n_out=4096, activation="relu", dropout=0.5, bias_init=1.0))
            .layer(OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(height, width, channels))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net
