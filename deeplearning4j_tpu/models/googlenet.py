"""GoogLeNet (Inception v1, Szegedy et al. 2014).

Reference: zoo/model/GoogLeNet.java (inception module :71-97 — four
branches 1x1 / 1x1→3x3 / 1x1→5x5 / maxpool→1x1 merged on the channel
axis; full graph :100-160).  Aux classifier heads are omitted as in the
reference's zoo build.
"""

from ..nn.conf.inputs import InputType
from ..nn.graph import ComputationGraph, GraphBuilder, MergeVertex
from ..nn.layers import (
    Convolution2D, DropoutLayer, GlobalPooling, LocalResponseNormalization,
    OutputLayer, Subsampling2D,
)
from ..nn.updaters import Adam


def _inception(b: GraphBuilder, name: str, inp: str,
               n1: int, r3: int, n3: int, r5: int, n5: int, pp: int) -> str:
    """Inception module (GoogLeNet.java:71-97): branch filter counts follow
    the paper's table-1 naming (#1x1, #3x3reduce, #3x3, #5x5reduce, #5x5,
    pool-proj)."""
    b.add_layer(f"{name}_1x1", Convolution2D(n_out=n1, kernel=(1, 1),
                convolution_mode="same", activation="relu"), inp)
    b.add_layer(f"{name}_3x3r", Convolution2D(n_out=r3, kernel=(1, 1),
                convolution_mode="same", activation="relu"), inp)
    b.add_layer(f"{name}_3x3", Convolution2D(n_out=n3, kernel=(3, 3),
                convolution_mode="same", activation="relu"), f"{name}_3x3r")
    b.add_layer(f"{name}_5x5r", Convolution2D(n_out=r5, kernel=(1, 1),
                convolution_mode="same", activation="relu"), inp)
    b.add_layer(f"{name}_5x5", Convolution2D(n_out=n5, kernel=(5, 5),
                convolution_mode="same", activation="relu"), f"{name}_5x5r")
    b.add_layer(f"{name}_pool", Subsampling2D(pooling="max", kernel=(3, 3),
                stride=(1, 1), convolution_mode="same"), inp)
    b.add_layer(f"{name}_poolp", Convolution2D(n_out=pp, kernel=(1, 1),
                convolution_mode="same", activation="relu"), f"{name}_pool")
    b.add_vertex(name, MergeVertex(),
                 f"{name}_1x1", f"{name}_3x3", f"{name}_5x5", f"{name}_poolp")
    return name


def GoogLeNet(height: int = 224, width: int = 224, channels: int = 3,
              num_classes: int = 1000, updater=None) -> ComputationGraph:
    b = (GraphBuilder()
         .seed(12345)
         .updater(updater if updater is not None else Adam(lr=1e-3))
         .add_inputs("in")
         .set_input_types(**{"in": InputType.convolutional(height, width, channels)}))
    b.add_layer("conv1", Convolution2D(n_out=64, kernel=(7, 7), stride=(2, 2),
                convolution_mode="same", activation="relu"), "in")
    b.add_layer("pool1", Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2),
                convolution_mode="same"), "conv1")
    b.add_layer("lrn1", LocalResponseNormalization(), "pool1")
    b.add_layer("conv2r", Convolution2D(n_out=64, kernel=(1, 1),
                convolution_mode="same", activation="relu"), "lrn1")
    b.add_layer("conv2", Convolution2D(n_out=192, kernel=(3, 3),
                convolution_mode="same", activation="relu"), "conv2r")
    b.add_layer("lrn2", LocalResponseNormalization(), "conv2")
    b.add_layer("pool2", Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2),
                convolution_mode="same"), "lrn2")
    x = _inception(b, "3a", "pool2", 64, 96, 128, 16, 32, 32)
    x = _inception(b, "3b", x, 128, 128, 192, 32, 96, 64)
    b.add_layer("pool3", Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2),
                convolution_mode="same"), x)
    x = _inception(b, "4a", "pool3", 192, 96, 208, 16, 48, 64)
    x = _inception(b, "4b", x, 160, 112, 224, 24, 64, 64)
    x = _inception(b, "4c", x, 128, 128, 256, 24, 64, 64)
    x = _inception(b, "4d", x, 112, 144, 288, 32, 64, 64)
    x = _inception(b, "4e", x, 256, 160, 320, 32, 128, 128)
    b.add_layer("pool4", Subsampling2D(pooling="max", kernel=(3, 3), stride=(2, 2),
                convolution_mode="same"), x)
    x = _inception(b, "5a", "pool4", 256, 160, 320, 32, 128, 128)
    x = _inception(b, "5b", x, 384, 192, 384, 48, 128, 128)
    b.add_layer("gap", GlobalPooling(pooling="avg"), x)
    b.add_layer("drop", DropoutLayer(dropout=0.4), "gap")
    b.add_layer("out", OutputLayer(n_out=num_classes, activation="softmax",
                                   loss="mcxent"), "drop")
    b.set_outputs("out")
    return ComputationGraph(b.build())
