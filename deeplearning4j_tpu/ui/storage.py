"""Stats storage — the persistence seam between collection and rendering.

Parity target: reference api/storage/StatsStorage.java +
InMemoryStatsStorage / FileStatsStorage / (MapDB) implementations.
Records are plain JSON dicts keyed by (session_id, worker_id, timestamp);
listeners can attach to storage for live routing (the reference's
StatsStorageListener callback path)."""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Callable, Dict, List, Optional


class BaseStatsStorage:
    """put_update / list_session_ids / get_updates + routing callbacks."""

    def __init__(self):
        self._listeners: List[Callable[[str, dict], None]] = []

    def register_listener(self, fn: Callable[[str, dict], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, session_id: str, record: dict) -> None:
        for fn in self._listeners:
            fn(session_id, record)

    # -- implemented by subclasses --
    def put_update(self, session_id: str, record: dict) -> None:
        raise NotImplementedError

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_updates(self, session_id: str) -> List[dict]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStatsStorage(BaseStatsStorage):
    """Reference InMemoryStatsStorage: ephemeral, for tests/UI sessions."""

    def __init__(self):
        super().__init__()
        self._data: Dict[str, List[dict]] = {}
        self._lock = threading.Lock()

    def put_update(self, session_id: str, record: dict) -> None:
        with self._lock:
            self._data.setdefault(session_id, []).append(record)
        self._notify(session_id, record)

    def list_session_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._data)

    def get_updates(self, session_id: str) -> List[dict]:
        with self._lock:
            return list(self._data.get(session_id, []))


class FileStatsStorage(BaseStatsStorage):
    """JSONL-per-session directory (reference FileStatsStorage's role:
    durable single-machine storage; JSONL instead of MapDB)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()

    def _file(self, session_id: str) -> str:
        safe = session_id.replace("/", "_")
        return os.path.join(self.path, f"{safe}.jsonl")

    def put_update(self, session_id: str, record: dict) -> None:
        with self._lock, open(self._file(session_id), "a") as f:
            f.write(json.dumps(record) + "\n")
        self._notify(session_id, record)

    def list_session_ids(self) -> List[str]:
        return sorted(os.path.splitext(f)[0] for f in os.listdir(self.path)
                      if f.endswith(".jsonl"))

    def get_updates(self, session_id: str) -> List[dict]:
        p = self._file(session_id)
        if not os.path.exists(p):
            return []
        with open(p) as f:
            return [json.loads(line) for line in f if line.strip()]


class SqliteStatsStorage(BaseStatsStorage):
    """Sqlite-backed storage — concurrent-reader friendly, queryable."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS updates ("
            "session_id TEXT, iteration INTEGER, record TEXT)")
        self._conn.execute(
            "CREATE INDEX IF NOT EXISTS idx_session ON updates(session_id)")
        self._conn.commit()

    def put_update(self, session_id: str, record: dict) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO updates VALUES (?, ?, ?)",
                (session_id, int(record.get("iteration", 0)), json.dumps(record)))
            self._conn.commit()
        self._notify(session_id, record)

    def list_session_ids(self) -> List[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT session_id FROM updates ORDER BY session_id")
            return [r[0] for r in rows.fetchall()]

    def get_updates(self, session_id: str) -> List[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT record FROM updates WHERE session_id=? ORDER BY iteration",
                (session_id,))
            return [json.loads(r[0]) for r in rows.fetchall()]

    def close(self) -> None:
        self._conn.close()
