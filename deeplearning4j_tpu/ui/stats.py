"""StatsListener — per-iteration training telemetry.

Parity target: reference ui/stats/BaseStatsListener.java:304-420
(iterationDone: score, timing, minibatch rate, param/update/activation
histograms + mean-magnitude ratios, JVM/off-heap memory) routed through a
StatsStorage.

TPU adaptation: params are per-layer pytrees, so per-layer stats come from
tree leaves; the fused jit step doesn't expose gradients, so the
update:param mean-magnitude ratio — the quantity DL4J users actually watch
(rule of thumb ~1e-3) — is computed from param DELTAS between iterations,
which under any SGD-family updater IS the applied update.  Device memory
comes from PJRT memory_stats() where the backend provides it (TPU yes,
CPU no).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..optimize.listeners import TrainingListener


def _leaf_stats(arr: np.ndarray, bins: int, with_histogram: bool = True
                ) -> Dict[str, Any]:
    a = np.asarray(arr, np.float32).ravel()
    if a.size == 0:
        return {}
    out = {
        "mean": float(a.mean()), "std": float(a.std()),
        "min": float(a.min()), "max": float(a.max()),
        "mean_magnitude": float(np.abs(a).mean()),
    }
    if with_histogram:
        hist, edges = np.histogram(a, bins=bins)
        out["histogram"] = hist.tolist()
        out["histogram_edges"] = [float(edges[0]), float(edges[-1])]
    return out


class StatsListener(TrainingListener):
    """Collects per-iteration stats into a StatsStorage.

    ``update_frequency`` throttles collection (reference updateFrequency);
    histograms are optional (they dominate record size, as in DL4J).

    ``clock`` is injectable (defaults to wall time): dashboard records
    are deliberately wall-anchored — session ids, timestamps and
    examples/sec all describe when training *actually* ran — but tests
    (and deterministic replays that diff record streams) can pin it.
    """

    def __init__(self, storage, session_id: Optional[str] = None,
                 update_frequency: int = 1, collect_histograms: bool = True,
                 histogram_bins: int = 20, collect_memory: bool = True,
                 collect_input_stats: bool = True,
                 clock=time.time):
        self.storage = storage
        self.clock = clock
        self.session_id = session_id or f"session_{int(self.clock())}"
        self.update_frequency = max(1, update_frequency)
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        self.collect_memory = collect_memory
        self.collect_input_stats = collect_input_stats
        self._last_time: Optional[float] = None
        self._last_params: Optional[List[Dict[str, np.ndarray]]] = None
        self._start_time = self.clock()

    # -- helpers -----------------------------------------------------------

    def _param_items(self, model):
        """Normalize MLN (list of dicts) / graph (dict of dicts) params to
        (layer_name, key, array) triples."""
        params = model.params
        if isinstance(params, dict):
            for name, p in params.items():
                for k, v in (p or {}).items():
                    yield name, k, v
        else:
            for i, p in enumerate(params):
                name = getattr(model.conf.layers[i], "name", None) or f"layer_{i}"
                for k, v in (p or {}).items():
                    yield name, k, v

    def _memory(self) -> Dict[str, Any]:
        try:
            import jax
            stats = jax.devices()[0].memory_stats()
            if stats:
                return {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
                        "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0))}
        except (ImportError, IndexError, AttributeError, RuntimeError):
            # no jax / no devices / backend without memory_stats (CPU)
            pass
        return {}

    # -- TrainingListener --------------------------------------------------

    # reads model.params each callback → needs each chunk's params, not
    # end-of-batch params, under fused multi-step (TBPTT scan) paths
    requires_model_state = True

    def iteration_done(self, model, iteration: int, loss: float) -> None:
        if iteration % self.update_frequency != 0:
            return
        now = self.clock()
        record: Dict[str, Any] = {
            "iteration": int(iteration),
            "timestamp": now,
            "relative_time": now - self._start_time,
            "score": float(loss),
        }
        if self._last_time is not None:
            dt = max(now - self._last_time, 1e-9)
            record["iterations_per_sec"] = self.update_frequency / dt
        self._last_time = now

        params_np = {}
        param_stats: Dict[str, Dict[str, Any]] = {}
        update_stats: Dict[str, Dict[str, Any]] = {}
        ratios: Dict[str, float] = {}
        for name, key, v in self._param_items(model):
            pid = f"{name}/{key}"
            arr = np.asarray(v)
            params_np[pid] = arr
            param_stats[pid] = _leaf_stats(arr, self.histogram_bins,
                                           self.collect_histograms)
            if self._last_params is not None and pid in self._last_params:
                delta = arr - self._last_params[pid]
                ustats = _leaf_stats(delta, self.histogram_bins,
                                     self.collect_histograms)
                update_stats[pid] = ustats
                pm = param_stats[pid].get("mean_magnitude", 0.0)
                um = ustats.get("mean_magnitude", 0.0)
                # the DL4J "mean magnitude ratio" users watch (~1e-3 healthy);
                # the delta spans update_frequency optimizer steps, so
                # normalize to a PER-STEP ratio
                ratios[pid] = (um / pm / self.update_frequency) if pm > 0 else 0.0
        self._last_params = params_np
        record["parameters"] = param_stats
        if update_stats:
            record["updates"] = update_stats
            record["update_ratios"] = ratios
        if self.collect_memory:
            mem = self._memory()
            if mem:
                record["memory"] = mem
        if self.collect_input_stats:
            # input-pipeline health rides the same record stream: stall
            # fraction ~0 = feeding hidden under compute, → 1 = the step
            # is infeed-bound (docs/INPUT_PIPELINE.md)
            from .profiler import input_pipeline_snapshot
            snap = input_pipeline_snapshot()
            if snap:
                record["input_pipeline"] = snap
        self.storage.put_update(self.session_id, record)

    def epoch_done(self, model, epoch: int) -> None:
        self.storage.put_update(self.session_id, {
            "iteration": int(getattr(model, "iteration", 0)),
            "timestamp": self.clock(),
            "epoch_done": int(epoch),
        })
