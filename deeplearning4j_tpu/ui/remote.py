"""Remote stats routing — train in one process, dashboard in another.

Parity targets: reference
deeplearning4j-core/.../api/storage/impl/RemoteUIStatsStorageRouter.java:32
(HTTP-POSTs serialized stats records to a UIServer with retry/backoff) and
deeplearning4j-ui-parent/deeplearning4j-play/.../module/remote/
RemoteReceiverModule.java (the /remote receiver endpoint).

``RemoteStatsRouter`` implements the same ``put_update(session_id,
record)`` surface as ui/storage.py's storages, so a ``StatsListener`` can
write to it unchanged; records become JSON POSTs to the receiving
``UIServer(enable_remote=True)``.  Failed posts are retried with capped
exponential backoff, then buffered and flushed on the next success —
matching the reference's retryCount/retryBackoffFactor semantics without
a background thread (posts happen on the listener's throttled cadence)."""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from typing import List, Optional

from ..obs import trace as obs_trace
from ..obs.metrics import get_registry

logger = logging.getLogger("deeplearning4j_tpu")


class RemoteStatsRouter:
    """StatsStorage-shaped router that POSTs updates to a remote UIServer.

    >>> router = RemoteStatsRouter("http://ui-host:9000")
    >>> net.add_listener(StatsListener(router))
    """

    def __init__(self, url: str, max_retries: int = 3,
                 backoff: float = 0.25, timeout: float = 5.0,
                 max_buffer: int = 1000):
        self.url = url.rstrip("/") + "/remote"
        self.max_retries = max_retries
        self.backoff = backoff
        self.timeout = timeout
        self.max_buffer = max_buffer
        self._pending: List[dict] = []
        self.dropped = 0
        # silent data loss is the failure mode a dashboard can't show:
        # dropped records count into the unified registry and the FIRST
        # drop warns loudly (once — steady-state drops would spam)
        self._dropped_counter = get_registry().counter(
            "ui_remote_dropped_records_total")
        self._drop_warned = False

    # -- StatsStorage surface (ui/storage.py contract) ---------------------

    def put_update(self, session_id: str, record: dict) -> None:
        self._pending.append({"session_id": session_id, "record": record})
        self.flush()

    def register_listener(self, fn) -> None:  # router has no local readers
        raise NotImplementedError(
            "RemoteStatsRouter is write-only — attach a storage on the "
            "UIServer side to read")

    def close(self) -> None:
        self.flush()

    # -- transport ---------------------------------------------------------

    def _post(self, items: List[dict]) -> bool:
        data = json.dumps(items).encode()
        delay = self.backoff
        for attempt in range(self.max_retries):
            try:
                req = urllib.request.Request(
                    self.url, data=data,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    return 200 <= r.status < 300
            except (urllib.error.URLError, OSError) as e:
                if attempt == self.max_retries - 1:
                    logger.warning("remote stats POST failed after %d tries: "
                                   "%s — buffering %d record(s)",
                                   self.max_retries, e, len(items))
                    return False
                time.sleep(delay)
                delay *= 2
        return False

    def flush(self) -> bool:
        """Try to deliver everything buffered; keep (bounded) on failure."""
        if not self._pending:
            return True
        if self._post(self._pending):
            self._pending = []
            return True
        overflow = len(self._pending) - self.max_buffer
        if overflow > 0:
            # drop OLDEST records; a dashboard cares about the recent ones
            self._pending = self._pending[overflow:]
            self.dropped += overflow
            self._dropped_counter.inc(overflow)
            obs_trace.instant("ui/remote_drop", cat="ui", dropped=overflow,
                              total_dropped=self.dropped)
            if not self._drop_warned:
                self._drop_warned = True
                logger.warning(
                    "RemoteStatsRouter is DROPPING stats records: buffer "
                    "full (max_buffer=%d) while %s is unreachable — %d "
                    "record(s) discarded so far; this warning fires once, "
                    "watch the ui_remote_dropped_records_total counter "
                    "(/metrics) for the running total",
                    self.max_buffer, self.url, self.dropped)
        return False
