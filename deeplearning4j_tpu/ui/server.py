"""Minimal training UI server.

Parity target: reference play/PlayUIServer.java (UIServer.getInstance()
.attach(statsStorage) → browse localhost:9000).  Stdlib http.server
renders the dashboard from the attached storage on every request — no
framework, no static assets, works air-gapped."""

from __future__ import annotations

import html
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from .render import render_session_html


class UIServer:
    """``UIServer(port).attach(storage).start()`` → browse /."""

    def __init__(self, port: int = 9000, host: str = "127.0.0.1",
                 enable_remote: bool = False):
        self.port = port
        self.host = host
        self.enable_remote = enable_remote
        self._storages: List = []
        self._metrics_providers: List = []
        self._engine = None
        self._decode_engine = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def attach(self, storage) -> "UIServer":
        self._storages.append(storage)
        return self

    def attach_metrics(self, provider) -> "UIServer":
        """Export a metrics source on GET /metrics.  ``provider`` is a
        zero-arg callable returning a JSON-able dict (e.g. a serving
        Engine's ``metrics_snapshot``) or an object with ``snapshot()``."""
        self._metrics_providers.append(provider)
        return self

    def attach_engine(self, engine) -> "UIServer":
        """Serve inference on POST /predict (JSON {"inputs": [[...]]} →
        {"outputs": ...}) through a serving Engine, and export its
        metrics on /metrics."""
        self._engine = engine
        return self.attach_metrics(engine.metrics_snapshot)

    def attach_decode_engine(self, engine) -> "UIServer":
        """Serve autoregressive generation on POST /generate (JSON
        {"prompt_ids": [...], "max_tokens": ..., "temperature": ...,
        "top_k": ..., "top_p": ..., "seed": ...} → {"tokens": [...]})
        through a serving DecodeEngine, and export its TTFT/TPOT
        histograms on /metrics — including the decode-speed counters
        (prefix_hits/misses/inserts/evictions, spec_steps/accepted/
        committed, shared_pages, accepted_tokens_per_step), which are
        present at zero when prefix caching / speculation are off so
        dashboards never see keys appear mid-flight."""
        self._decode_engine = engine
        return self.attach_metrics(engine.metrics_snapshot)

    def _metrics_json(self) -> str:
        import json

        from ..obs.metrics import get_registry
        serving = []
        for p in self._metrics_providers:
            snap = p() if callable(p) else p.snapshot()
            serving.append(snap)
        sessions = {}
        for storage in self._storages:
            for sid in storage.list_session_ids():
                ups = storage.get_updates(sid)
                last = ups[-1] if ups else {}
                sessions[sid] = {"updates": len(ups),
                                 "last_iteration": last.get("iteration"),
                                 "last_score": last.get("score")}
        # the unified registry (docs/OBSERVABILITY.md): serving engines,
        # elastic recovery counters, input-pipeline stall stats, launcher
        # membership — one schema beside the legacy keys
        return json.dumps({"serving": serving, "sessions": sessions,
                           "registry": get_registry().snapshot()})

    def _trace_json(self) -> str:
        """GET /trace: the ring buffer as a Chrome trace-event JSON
        object (load in chrome://tracing or ui.perfetto.dev); an empty
        trace with a hint when tracing is off."""
        import json

        from ..obs import trace as obs_trace
        rec = obs_trace.get_recorder()
        if rec is None:
            return json.dumps({"traceEvents": [], "metadata": {
                "tracing": "disabled — enable with --trace PATH or "
                           "obs.enable_tracing()"}})
        return json.dumps(rec.export())

    def _predict_json(self, body: bytes):
        """(status, payload) for POST /predict.  Every error is
        structured JSON with a STABLE ``error_class`` field (never a raw
        traceback): admission shed → 429 ``overloaded``, a blown
        deadline → 504 ``deadline_exceeded``, an isolated poison input →
        422 ``poison_input``, malformed request → 400 ``bad_request``,
        anything else → 500 ``internal`` (exception type + message only
        — model internals stay out of the HTTP surface)."""
        import json
        from ..serving import (
            DeadlineExceededError, ModelNotLoadedError, OverloadedError,
            PoisonInputError, ReplicaCrashError, ReplicaHungError,
            TenantOverloadedError,
        )
        if self._engine is None:
            return 503, {"error": "no serving engine attached",
                         "error_class": "unavailable"}
        try:
            payload = json.loads(body)
            import numpy as np
            x = np.asarray(payload["inputs"], np.float32)
            kw = {}
            # optional multi-tenant fields: only forwarded when present,
            # so a duck-typed engine predating tenancy still works
            if payload.get("tenant") is not None:
                kw["tenant"] = str(payload["tenant"])
            if payload.get("model") is not None:
                kw["model"] = str(payload["model"])
            out = self._engine.output(x, slo_ms=payload.get("slo_ms"), **kw)
            return 200, {"outputs": np.asarray(out).tolist(),
                         "model": self._engine.current_tag}
        except TenantOverloadedError as e:
            # the tenant's OWN quota — distinct from fleet overload, so
            # clients can tell whose budget ran out (and back off, not
            # retry elsewhere)
            return 429, {"error": str(e), "error_class": "tenant_overloaded",
                         "tenant": e.tenant, "shed_count": e.shed_count,
                         "reason": e.reason}
        except OverloadedError as e:
            return 429, {"error": str(e), "error_class": "overloaded"}
        except ModelNotLoadedError as e:
            return 404, {"error": str(e), "error_class": "model_not_loaded"}
        except DeadlineExceededError as e:
            return 504, {"error": str(e), "error_class": "deadline_exceeded"}
        except PoisonInputError as e:
            return 422, {"error": str(e), "error_class": "poison_input"}
        except (ReplicaCrashError, ReplicaHungError) as e:
            return 500, {"error": str(e), "error_class": "replica_failure"}
        except (KeyError, ValueError, TypeError) as e:
            return 400, {"error": f"{type(e).__name__}: {e}",
                         "error_class": "bad_request"}
        except Exception as e:  # model exceptions: no traceback leak
            return 500, {"error": f"{type(e).__name__}: {e}",
                         "error_class": "internal"}

    def _generate_json(self, body: bytes):
        """(status, payload) for POST /generate — the decode-engine
        twin of ``_predict_json``, same structured-error contract:
        shed → 429 ``overloaded``, blown QUEUED deadline → 504
        ``deadline_exceeded`` (a deadline hit MID-decode returns 200
        with ``finish_reason: "deadline"`` and the tokens produced
        inside the budget), non-finite logits → 422 ``poison_input``,
        exhausted crash retries → 500 ``replica_failure``, malformed
        request → 400 ``bad_request``."""
        import json
        from ..serving import (
            DeadlineExceededError, ModelNotLoadedError, OverloadedError,
            PoisonInputError, ReplicaCrashError, ReplicaHungError,
            TenantOverloadedError,
        )
        if self._decode_engine is None:
            return 503, {"error": "no decode engine attached",
                         "error_class": "unavailable"}
        if getattr(self._decode_engine, "role", "unified") == "prefill":
            # a prefill-role host emits page-handoff batons, not tokens —
            # only a FleetRouter can route those to a decode-role sink
            return 409, {"error": "this host is a prefill-role engine; "
                                  "its output is a KV-page handoff, not "
                                  "tokens — send /generate traffic to a "
                                  "fleet router or a unified/decode host",
                         "error_class": "prefill_role"}
        try:
            payload = json.loads(body)
            kw = {}
            if payload.get("tenant") is not None:
                kw["tenant"] = str(payload["tenant"])
            if payload.get("model") is not None:
                kw["model"] = str(payload["model"])
            res = self._decode_engine.generate(
                payload["prompt_ids"],
                max_new_tokens=payload.get("max_tokens"),
                temperature=payload.get("temperature", 0.0),
                top_k=payload.get("top_k", 0),
                top_p=payload.get("top_p", 1.0),
                seed=payload.get("seed", 0),
                slo_ms=payload.get("slo_ms"), **kw)
            return 200, {"tokens": res.tokens, "n_prompt": res.n_prompt,
                         "finish_reason": res.finish_reason,
                         "model": res.model_tag, "ttft_ms": res.ttft_ms,
                         "tpot_ms": res.tpot_ms}
        except TenantOverloadedError as e:
            return 429, {"error": str(e), "error_class": "tenant_overloaded",
                         "tenant": e.tenant, "shed_count": e.shed_count,
                         "reason": e.reason}
        except OverloadedError as e:
            return 429, {"error": str(e), "error_class": "overloaded"}
        except ModelNotLoadedError as e:
            return 404, {"error": str(e), "error_class": "model_not_loaded"}
        except DeadlineExceededError as e:
            return 504, {"error": str(e), "error_class": "deadline_exceeded"}
        except PoisonInputError as e:
            return 422, {"error": str(e), "error_class": "poison_input"}
        except (ReplicaCrashError, ReplicaHungError) as e:
            return 500, {"error": str(e), "error_class": "replica_failure"}
        except (KeyError, ValueError, TypeError) as e:
            return 400, {"error": f"{type(e).__name__}: {e}",
                         "error_class": "bad_request"}
        except Exception as e:  # model exceptions: no traceback leak
            return 500, {"error": f"{type(e).__name__}: {e}",
                         "error_class": "internal"}

    def _healthz_json(self):
        """(status, payload) for GET /healthz: liveness + readiness with
        per-replica health (healthy/degraded/dead) from the engine's
        supervisor.  Readiness covers EVERY attached engine — a host
        serving only decode traffic answers from its DecodeEngine's
        health, not a blanket 503 (ready-with-no-evidence is as wrong as
        unready-with-evidence).  With both engines attached, ready means
        BOTH are ready (each serves its own endpoint; a dead one must
        take the box out of rotation).  503 when nothing is attached or
        some attached engine is not dispatchable."""
        engines = {}
        if self._engine is not None:
            engines["predict"] = self._engine
        if self._decode_engine is not None:
            engines["decode"] = self._decode_engine
        if not engines:
            return 503, {"status": "unready", "ready": False,
                         "error": "no serving engine attached"}

        def _snap(e):
            s = e.health_snapshot()
            tag = getattr(e, "current_tag", None)
            if tag and "model" not in s:   # lets a remote FleetRouter
                s["model"] = tag           # read each host's live tag
            return s

        if len(engines) == 1:
            snap = _snap(next(iter(engines.values())))
            return (200 if snap.get("ready") else 503), snap
        per = {k: _snap(e) for k, e in engines.items()}
        ready = all(s.get("ready") for s in per.values())
        status = ("ok" if all(s.get("status") == "ok"
                              for s in per.values())
                  else "degraded" if ready else "unready")
        return (200 if ready else 503), {"status": status, "ready": ready,
                                         "engines": per}

    def enable_remote_listener(self) -> "UIServer":
        """Accept POSTed stats on /remote into the first attached storage
        (reference RemoteReceiverModule: UIServer.enableRemoteListener())."""
        self.enable_remote = True
        return self

    def _handle_remote(self, body: bytes) -> int:
        """POST /remote body: {"session_id": ..., "record": {...}} or a
        list of such — returns HTTP status."""
        import json
        if not self.enable_remote:
            return 403
        if not self._storages:
            return 503
        payload = json.loads(body)
        items = payload if isinstance(payload, list) else [payload]
        for item in items:
            self._storages[0].put_update(item["session_id"], item["record"])
        return 200

    def _render_index(self) -> str:
        rows = []
        for si, storage in enumerate(self._storages):
            for sid in storage.list_session_ids():
                href = f"/train/{si}/{urllib.parse.quote(sid, safe='')}"
                rows.append(f'<li><a href="{href}">'
                            f"{html.escape(sid)}</a></li>")
        return ("<html><body><h1>deeplearning4j_tpu UI</h1><ul>"
                + "".join(rows) + "</ul></body></html>")

    def start(self) -> "UIServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence request logging
                pass

            def _reply(self, code, data, ctype):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    path = urllib.parse.urlsplit(self.path).path
                    if path.startswith("/train/"):
                        _, _, si, sid = path.split("/", 3)
                        body = render_session_html(
                            server._storages[int(si)],
                            urllib.parse.unquote(sid))
                    elif path == "/metrics":
                        self._reply(200, server._metrics_json().encode(),
                                    "application/json")
                        return
                    elif path == "/trace":
                        self._reply(200, server._trace_json().encode(),
                                    "application/json")
                        return
                    elif path == "/healthz":
                        import json as _json
                        code, payload = server._healthz_json()
                        self._reply(code, _json.dumps(payload).encode(),
                                    "application/json")
                        return
                    elif path in ("", "/", "/index.html"):
                        body = server._render_index()
                    else:  # unknown paths are 404s, not the index page
                        self._reply(404, b"not found", "text/plain")
                        return
                    self._reply(200, body.encode(),
                                "text/html; charset=utf-8")
                except Exception as e:  # pragma: no cover - defensive
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

            def do_POST(self):
                try:
                    import json
                    n = int(self.headers.get("Content-Length", 0))
                    if self.path == "/predict":
                        code, payload = server._predict_json(self.rfile.read(n))
                        self._reply(code, json.dumps(payload).encode(),
                                    "application/json")
                        return
                    if self.path == "/generate":
                        code, payload = server._generate_json(
                            self.rfile.read(n))
                        self._reply(code, json.dumps(payload).encode(),
                                    "application/json")
                        return
                    if self.path != "/remote":
                        self.send_response(404)
                        self.end_headers()
                        return
                    code = server._handle_remote(self.rfile.read(n))
                    self.send_response(code)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                except Exception:
                    self.send_response(400)
                    self.end_headers()

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolves port=0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
