"""Minimal training UI server.

Parity target: reference play/PlayUIServer.java (UIServer.getInstance()
.attach(statsStorage) → browse localhost:9000).  Stdlib http.server
renders the dashboard from the attached storage on every request — no
framework, no static assets, works air-gapped."""

from __future__ import annotations

import html
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from .render import render_session_html


class UIServer:
    """``UIServer(port).attach(storage).start()`` → browse /."""

    def __init__(self, port: int = 9000, host: str = "127.0.0.1",
                 enable_remote: bool = False):
        self.port = port
        self.host = host
        self.enable_remote = enable_remote
        self._storages: List = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def attach(self, storage) -> "UIServer":
        self._storages.append(storage)
        return self

    def enable_remote_listener(self) -> "UIServer":
        """Accept POSTed stats on /remote into the first attached storage
        (reference RemoteReceiverModule: UIServer.enableRemoteListener())."""
        self.enable_remote = True
        return self

    def _handle_remote(self, body: bytes) -> int:
        """POST /remote body: {"session_id": ..., "record": {...}} or a
        list of such — returns HTTP status."""
        import json
        if not self.enable_remote:
            return 403
        if not self._storages:
            return 503
        payload = json.loads(body)
        items = payload if isinstance(payload, list) else [payload]
        for item in items:
            self._storages[0].put_update(item["session_id"], item["record"])
        return 200

    def _render_index(self) -> str:
        rows = []
        for si, storage in enumerate(self._storages):
            for sid in storage.list_session_ids():
                href = f"/train/{si}/{urllib.parse.quote(sid, safe='')}"
                rows.append(f'<li><a href="{href}">'
                            f"{html.escape(sid)}</a></li>")
        return ("<html><body><h1>deeplearning4j_tpu UI</h1><ul>"
                + "".join(rows) + "</ul></body></html>")

    def start(self) -> "UIServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence request logging
                pass

            def do_GET(self):
                try:
                    if self.path.startswith("/train/"):
                        _, _, si, sid = self.path.split("/", 3)
                        body = render_session_html(
                            server._storages[int(si)],
                            urllib.parse.unquote(sid))
                    else:
                        body = server._render_index()
                    data = body.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except Exception as e:  # pragma: no cover - defensive
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

            def do_POST(self):
                try:
                    if self.path != "/remote":
                        self.send_response(404)
                        self.end_headers()
                        return
                    n = int(self.headers.get("Content-Length", 0))
                    code = server._handle_remote(self.rfile.read(n))
                    self.send_response(code)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                except Exception:
                    self.send_response(400)
                    self.end_headers()

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolves port=0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
