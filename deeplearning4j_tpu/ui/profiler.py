"""jax.profiler integration — the deep-performance seam.

The reference polls SystemInfo/JVM stats (ui/module SystemInfoController);
on TPU the right tool is the XLA profiler: ``profile_trace(logdir)``
captures a TensorBoard-compatible trace (HLO timelines, memory viewer,
op-level MXU utilization) around any training region."""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def profile_trace(logdir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Context manager: ``with profile_trace('/tmp/trace'): train()`` —
    view with TensorBoard's profile plugin (or perfetto).  No-ops cleanly
    if the profiler backend is unavailable."""
    import jax

    try:
        jax.profiler.start_trace(logdir,
                                 create_perfetto_link=create_perfetto_link)
        started = True
    except Exception:   # profiler unavailable on this backend/build
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
