"""jax.profiler integration — the deep-performance seam.

The reference polls SystemInfo/JVM stats (ui/module SystemInfoController);
on TPU the right tool is the XLA profiler: ``profile_trace(logdir)``
captures a TensorBoard-compatible trace (HLO timelines, memory viewer,
op-level MXU utilization) around any training region."""

from __future__ import annotations

import contextlib
from typing import Iterator, List


def input_pipeline_snapshot() -> List[dict]:
    """Stall-fraction / queue-depth snapshots of every live
    DevicePrefetchIterator (datasets/device_prefetch.py) — the
    input-pipeline counterpart of the XLA trace: stall_fraction ~0 means
    input feeding is fully hidden under device compute, → 1 means the
    step is infeed-bound (docs/INPUT_PIPELINE.md has the interpretation
    table).  Collected by StatsListener each iteration; empty list when
    no prefetcher is active."""
    try:
        from ..datasets.device_prefetch import live_pipelines
    except Exception:   # pragma: no cover — partial install
        return []
    return [p.stall_stats() for p in live_pipelines()]


@contextlib.contextmanager
def profile_trace(logdir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Context manager: ``with profile_trace('/tmp/trace'): train()`` —
    view with TensorBoard's profile plugin (or perfetto; the
    ``create_perfetto_link`` path stays available where the TPU backend
    supports it).  Degrades gracefully when the profiler backend is
    unavailable (CPU CI, stripped jaxlib builds): instead of raising, the
    region runs untraced and a ``profiler/unavailable`` instant event is
    recorded into the span trace (obs/trace.py) so the gap is visible in
    the timeline rather than silent."""
    from ..obs import trace as obs_trace

    started = False
    try:
        import jax

        jax.profiler.start_trace(logdir,
                                 create_perfetto_link=create_perfetto_link)
        started = True
    except Exception as e:   # profiler unavailable on this backend/build
        obs_trace.instant("profiler/unavailable", cat="profiler",
                          logdir=logdir, error=f"{type(e).__name__}: {e}")
    try:
        with obs_trace.span("profiler/trace", cat="profiler", logdir=logdir,
                            backend_started=started):
            yield
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                # a failed stop loses the on-chip trace: record it on
                # the span timeline instead of dropping it silently
                obs_trace.instant("profiler/stop_failed", cat="profiler",
                                  logdir=logdir,
                                  error=f"{type(e).__name__}: {e}")
