"""Static HTML dashboard rendering.

Parity target: the reference's train UI pages (overview: score vs
iteration, update:param ratios, performance; model: per-layer histograms —
deeplearning4j-ui rendering of StatsStorage).  Zero-egress inversion: a
single self-contained HTML file with inline SVG charts, no external
scripts; re-render (or use UIServer) for live-ish updates.
"""

from __future__ import annotations

import html
import math
from typing import Dict, List, Optional, Sequence, Tuple


def _polyline(xs: Sequence[float], ys: Sequence[float], w: int = 560,
              h: int = 180, color: str = "#2563eb", logy: bool = False) -> str:
    if not xs or not ys:
        return "<svg/>"
    yv = [(math.log10(max(v, 1e-12)) if logy else v) for v in ys]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(yv), max(yv)
    xr = (x1 - x0) or 1.0
    yr = (y1 - y0) or 1.0
    pts = " ".join(
        f"{10 + (x - x0) / xr * (w - 20):.1f},{h - 15 - (y - y0) / yr * (h - 30):.1f}"
        for x, y in zip(xs, yv))
    lab_top = f"{(10 ** y1 if logy else y1):.4g}"
    lab_bot = f"{(10 ** y0 if logy else y0):.4g}"
    return (f'<svg width="{w}" height="{h}" style="background:#fafafa;'
            f'border:1px solid #ddd">'
            f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
            f'points="{pts}"/>'
            f'<text x="4" y="12" font-size="10" fill="#666">{lab_top}</text>'
            f'<text x="4" y="{h - 4}" font-size="10" fill="#666">{lab_bot}</text>'
            f'</svg>')


def _histogram_svg(hist: List[int], edges: List[float], w: int = 260,
                   h: int = 90, color: str = "#059669") -> str:
    if not hist:
        return "<svg/>"
    mx = max(hist) or 1
    n = len(hist)
    bw = (w - 20) / n
    bars = "".join(
        f'<rect x="{10 + i * bw:.1f}" y="{h - 12 - v / mx * (h - 24):.1f}" '
        f'width="{max(bw - 1, 1):.1f}" height="{v / mx * (h - 24):.1f}" '
        f'fill="{color}"/>' for i, v in enumerate(hist))
    return (f'<svg width="{w}" height="{h}" style="background:#fafafa;'
            f'border:1px solid #ddd">{bars}'
            f'<text x="4" y="{h - 2}" font-size="9" fill="#666">{edges[0]:.3g}</text>'
            f'<text x="{w - 40}" y="{h - 2}" font-size="9" fill="#666">{edges[1]:.3g}</text>'
            f'</svg>')


def render_session_html(storage, session_id: str) -> str:
    updates = [u for u in storage.get_updates(session_id) if "score" in u]
    if not updates:
        return (f"<html><body><h2>{html.escape(session_id)}</h2>"
                "<p>no updates recorded</p></body></html>")
    its = [u["iteration"] for u in updates]
    scores = [u["score"] for u in updates]
    rates = [(u["iteration"], u["iterations_per_sec"]) for u in updates
             if "iterations_per_sec" in u]
    mems = [(u["iteration"], u["memory"]["bytes_in_use"] / 2**20)
            for u in updates if "memory" in u]
    last = updates[-1]

    parts = [
        "<html><head><meta charset='utf-8'><title>deeplearning4j_tpu — ",
        html.escape(session_id),
        "</title><style>body{font-family:sans-serif;margin:20px;color:#111}"
        "h2{margin:18px 0 6px}table{border-collapse:collapse;font-size:12px}"
        "td,th{border:1px solid #ccc;padding:3px 8px;text-align:right}"
        "th{background:#f3f4f6}.grid{display:flex;flex-wrap:wrap;gap:14px}"
        ".card{font-size:11px;color:#444}</style></head><body>",
        f"<h1>Training session: {html.escape(session_id)}</h1>",
        f"<p>{len(updates)} updates · final score {scores[-1]:.5f}</p>",
        "<h2>Score vs iteration (log)</h2>", _polyline(its, scores, logy=True),
    ]
    if rates:
        parts += ["<h2>Iterations / sec</h2>",
                  _polyline([r[0] for r in rates], [r[1] for r in rates],
                            color="#d97706")]
    if mems:
        parts += ["<h2>Device memory in use (MB)</h2>",
                  _polyline([m[0] for m in mems], [m[1] for m in mems],
                            color="#dc2626")]

    ratios = last.get("update_ratios", {})
    if ratios:
        series: Dict[str, Tuple[List[float], List[float]]] = {}
        for u in updates:
            for pid, r in u.get("update_ratios", {}).items():
                series.setdefault(pid, ([], []))
                series[pid][0].append(u["iteration"])
                series[pid][1].append(max(r, 1e-12))
        parts.append("<h2>Update : parameter mean-magnitude ratio (log; "
                     "healthy ≈ 1e-3)</h2><div class='grid'>")
        for pid, (xs, ys) in sorted(series.items()):
            parts.append(f"<div class='card'>{html.escape(pid)}<br>"
                         + _polyline(xs, ys, w=260, h=90, color="#7c3aed",
                                     logy=True) + "</div>")
        parts.append("</div>")

    pstats = last.get("parameters", {})
    if pstats:
        parts.append("<h2>Parameter stats (last iteration)</h2><table>"
                     "<tr><th>param</th><th>mean</th><th>std</th><th>min</th>"
                     "<th>max</th></tr>")
        for pid, st in sorted(pstats.items()):
            if st:
                parts.append(
                    f"<tr><td style='text-align:left'>{html.escape(pid)}</td>"
                    f"<td>{st['mean']:.4g}</td><td>{st['std']:.4g}</td>"
                    f"<td>{st['min']:.4g}</td><td>{st['max']:.4g}</td></tr>")
        parts.append("</table>")
        hists = [(pid, st) for pid, st in sorted(pstats.items())
                 if st.get("histogram")]
        if hists:
            parts.append("<h2>Parameter histograms (last iteration)</h2>"
                         "<div class='grid'>")
            for pid, st in hists:
                parts.append(f"<div class='card'>{html.escape(pid)}<br>"
                             + _histogram_svg(st["histogram"],
                                              st["histogram_edges"]) + "</div>")
            parts.append("</div>")
    parts.append("</body></html>")
    return "".join(parts)


def render_dashboard(storage, path: str,
                     session_id: Optional[str] = None) -> str:
    """Write a self-contained HTML report for one session (default: the
    latest) and return the path."""
    sessions = storage.list_session_ids()
    if not sessions:
        raise ValueError("storage has no sessions")
    sid = session_id or sessions[-1]
    html_text = render_session_html(storage, sid)
    with open(path, "w") as f:
        f.write(html_text)
    return path


def render_embedding_html(coords, labels=None, words: Optional[Sequence[str]] = None,
                          title: str = "t-SNE embedding",
                          w: int = 720, h: int = 720) -> str:
    """Self-contained scatter page for 2-D embeddings — the reference UI's
    t-SNE viewer (deeplearning4j-play TsneModule: upload coords, render a
    point cloud).  ``coords`` [N,2]; ``labels`` optional int classes
    (colors); ``words`` optional hover/annotation strings (first 200 get
    text annotations, all get <title> hovers)."""
    import numpy as np

    c = np.asarray(coords, float)
    if c.ndim != 2 or c.shape[1] != 2:
        raise ValueError(f"coords must be [N,2], got {c.shape}")
    n = len(c)
    if words is not None and len(words) != n:
        raise ValueError(f"{len(words)} words for {n} points")
    if n == 0:
        return ("<!doctype html><html><body style='font-family:system-ui'>"
                f"<h2>{html.escape(title)}</h2><p>0 points</p></body></html>")
    x0, y0 = c.min(axis=0)
    x1, y1 = c.max(axis=0)
    xr = (x1 - x0) or 1.0
    yr = (y1 - y0) or 1.0
    palette = ["#2563eb", "#dc2626", "#059669", "#d97706", "#7c3aed",
               "#0891b2", "#be185d", "#4d7c0f", "#b45309", "#1e40af"]
    lab = None if labels is None else np.asarray(labels)
    pts = []
    for i in range(n):
        px = 20 + (c[i, 0] - x0) / xr * (w - 40)
        py = h - 20 - (c[i, 1] - y0) / yr * (h - 40)
        color = palette[int(lab[i]) % len(palette)] if lab is not None \
            else "#2563eb"
        tip = html.escape(str(words[i])) if words is not None else str(i)
        pts.append(f'<circle cx="{px:.1f}" cy="{py:.1f}" r="2.5" '
                   f'fill="{color}" fill-opacity="0.7"><title>{tip}</title>'
                   f'</circle>')
        if words is not None and i < 200:
            pts.append(f'<text x="{px + 3:.1f}" y="{py - 3:.1f}" '
                       f'font-size="8" fill="#444">{html.escape(str(words[i]))}'
                       f'</text>')
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title></head><body "
            "style='font-family:system-ui;margin:16px'>"
            f"<h2>{html.escape(title)}</h2><p>{n} points</p>"
            f'<svg width="{w}" height="{h}" style="background:#fafafa;'
            f'border:1px solid #ddd">{"".join(pts)}</svg></body></html>')
