"""Observability: stats collection → storage → dashboard (replaces
deeplearning4j-ui-parent, SURVEY.md §1 L6).

The reference splits this into BaseStatsListener (per-iteration collection)
→ StatsStorage (routing/persistence) → Play web server (rendering).  The
same three seams exist here, TPU-shaped: the listener reads the model's
pytrees (no flat param buffer), storage is in-memory / JSONL / sqlite, and
rendering emits a self-contained static HTML dashboard (zero-egress: no
CDN scripts, inline SVG) served optionally by a stdlib http server.
jax.profiler integration replaces the reference's SystemInfo polling for
deep performance traces.
"""

from .stats import StatsListener
from .storage import FileStatsStorage, InMemoryStatsStorage, SqliteStatsStorage
from .render import render_dashboard, render_embedding_html
from .remote import RemoteStatsRouter
from .server import UIServer
from .profiler import input_pipeline_snapshot, profile_trace

__all__ = [
    "StatsListener",
    "InMemoryStatsStorage", "FileStatsStorage", "SqliteStatsStorage",
    "render_dashboard", "render_embedding_html", "RemoteStatsRouter", "UIServer", "profile_trace",
    "input_pipeline_snapshot",
]
